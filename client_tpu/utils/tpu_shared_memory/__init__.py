"""TPU shared-memory data plane — the CUDA-IPC replacement.

The reference moves *device* tensors between client and server processes via
``cudaIpcMemHandle_t`` (reference
src/python/library/tritonclient/utils/cuda_shared_memory/__init__.py:107-170).
TPUs have no cross-process device-buffer IPC: HBM is owned by one libtpu
process. The TPU-native equivalent (BASELINE.json north star) is a **shared
pinned host buffer**:

- a region is a POSIX shared-memory buffer both processes map;
- the client stages ``jax.Array``s into it with ONE batched device→host
  transfer for all arrays (``set_shared_memory_region_from_jax``) followed
  by one host-side memcpy per array into the mapped pages — the transfer,
  not the memcpy, is the cost that matters: a device→host trip has a flat
  ~67 ms cost through a TPU relay regardless of array count (PERF.md), so
  batching N arrays into one ``jax.device_get`` pays that flat cost once;
- host tensors (numpy / DLPack exporters) copy straight into the mapped
  pages with no intermediate buffer;
- the raw handle exchanged over the wire (``get_raw_handle``) is a JSON
  document carrying the shm key + framing, registered via
  ``register_tpu_shared_memory`` on either protocol client;
- the server maps the same pages and reads them zero-copy
  (``as_shared_memory_tensor`` / ``get_contents_as_numpy`` are views over
  the mapping; ``as_jax_array`` adds the one H2D transfer).

Measured copy count per staging call (device side): 1 batched D2H transfer
+ 1 host memcpy per array. The region is plain POSIX shm (not libtpu-
registered); cross-process sharing of the bytes is zero-copy, the device
boundary costs one transfer per direction.
"""

import json
import threading
import uuid
from typing import Dict, List, Optional

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    num_elements,
    serialize_byte_tensor,
    triton_to_np_dtype,
)
from client_tpu.utils import shared_memory as _system_shm
from client_tpu.utils._dlpack import SharedMemoryTensor, consume_dlpack_capsule

_allocated_lock = threading.Lock()
_allocated_regions: Dict[str, "TpuSharedMemoryRegion"] = {}

HANDLE_KIND = "tpu-host-pinned"


class TpuSharedMemoryException(InferenceServerException):
    """Raised for TPU shared-memory errors."""


class TpuSharedMemoryRegion:
    """Handle to an allocated TPU shared-memory region."""

    def __init__(self, triton_shm_name: str, byte_size: int, device_id: int):
        self._name = triton_shm_name
        self._byte_size = byte_size
        self._device_id = device_id
        self._shm_key = f"client_tpu_shm_{uuid.uuid4().hex}"
        self._base = _system_shm.create_shared_memory_region(
            triton_shm_name, self._shm_key, byte_size, create_only=True
        )

    def name(self) -> str:
        return self._name

    def key(self) -> str:
        return self._shm_key

    def byte_size(self) -> int:
        return self._byte_size

    def device_id(self) -> int:
        return self._device_id

    def buf(self, offset: int = 0, length: Optional[int] = None):
        return self._base.buf(offset, length)

    def _destroy(self) -> None:
        _system_shm.destroy_shared_memory_region(self._base)


def create_shared_memory_region(
    triton_shm_name: str, byte_size: int, device_id: int = 0
) -> TpuSharedMemoryRegion:
    """Allocate a TPU shared-memory region of ``byte_size`` bytes.

    API twin of the reference's cudaMalloc+cudaIpcGetMemHandle
    (reference cuda_shared_memory/__init__.py:107-149); here the allocation
    is a shared pinned host buffer adjacent to TPU ``device_id``.
    """
    region = TpuSharedMemoryRegion(triton_shm_name, byte_size, device_id)
    with _allocated_lock:
        _allocated_regions[triton_shm_name] = region
    return region


def get_raw_handle(shm_handle: TpuSharedMemoryRegion) -> bytes:
    """The serialized region handle to pass to register_tpu_shared_memory.

    (Reference twin: base64 of cudaIpcMemHandle reserved bytes,
    reference cuda_shared_memory/__init__.py:152-170.)
    """
    return json.dumps(
        {
            "kind": HANDLE_KIND,
            "shm_key": shm_handle.key(),
            "byte_size": shm_handle.byte_size(),
            "device_id": shm_handle.device_id(),
        }
    ).encode("utf-8")


def set_shared_memory_region(
    shm_handle: TpuSharedMemoryRegion, input_values, offset: int = 0
) -> None:
    """Copy numpy arrays into the region back-to-back from ``offset``."""
    if not isinstance(input_values, (list, tuple)):
        raise TpuSharedMemoryException(
            "input_values must be a list/tuple of arrays"
        )
    cursor = offset
    for arr in input_values:
        arr = np.asarray(arr)
        if arr.dtype == np.dtype(object) or arr.dtype.kind in ("S", "U"):
            payload = serialize_byte_tensor(arr).tobytes()
            view = shm_handle.buf(cursor, len(payload))
            view[:] = payload
            cursor += len(payload)
        else:
            arr = np.ascontiguousarray(arr)
            view = shm_handle.buf(cursor, arr.nbytes)
            # single memcpy into the shared mapping, no intermediate bytes()
            np.frombuffer(view, dtype=arr.dtype).reshape(arr.shape)[...] = arr
            cursor += arr.nbytes


def set_shared_memory_region_from_jax(
    shm_handle: TpuSharedMemoryRegion, jax_arrays, offset: int = 0
) -> None:
    """Stage jax.Arrays into the region back-to-back from ``offset``.

    ONE batched device→host transfer moves every array (``jax.device_get``
    of the whole list — a per-transfer flat cost of ~67 ms through a TPU
    relay makes per-array readbacks N× slower; PERF.md), then each array is
    memcpy'd into the mapped pages. Host-resident arrays skip the device
    transfer entirely.
    """
    if not isinstance(jax_arrays, (list, tuple)):
        jax_arrays = [jax_arrays]
    try:
        import jax

        hosts = jax.device_get(list(jax_arrays))  # ONE batched D2H transfer
    except Exception:  # noqa: BLE001 - plain numpy/non-jax inputs
        hosts = jax_arrays
    cursor = offset
    for host in hosts:
        host = np.ascontiguousarray(host)
        view = shm_handle.buf(cursor, host.nbytes)
        np.frombuffer(view, dtype=host.dtype).reshape(host.shape)[...] = host
        cursor += host.nbytes


def set_shared_memory_region_from_dlpack(
    shm_handle: TpuSharedMemoryRegion, input_values, offset: int = 0
) -> None:
    """Copy DLPack-exporting tensors (torch/jax/numpy) into the region."""
    if not isinstance(input_values, (list, tuple)):
        input_values = [input_values]
    cursor = offset
    for tensor in input_values:
        if hasattr(tensor, "__dlpack__"):
            try:
                arr = consume_dlpack_capsule(tensor.__dlpack__())
            except (ValueError, TypeError):
                # device tensor or exotic layout: stage through the host
                arr = np.asarray(tensor)
        else:
            arr = np.asarray(tensor)
        view = shm_handle.buf(cursor, arr.nbytes)
        np.frombuffer(view, dtype=arr.dtype).reshape(arr.shape)[...] = arr
        cursor += arr.nbytes


def get_contents_as_numpy(
    shm_handle: TpuSharedMemoryRegion,
    datatype,
    shape: List[int],
    offset: int = 0,
) -> np.ndarray:
    """View region contents as numpy (zero-copy for fixed-size dtypes).

    ``datatype`` may be a numpy dtype or a KServe dtype string ("BF16"...).
    """
    from client_tpu.utils import deserialize_bytes_tensor

    if isinstance(datatype, str):
        np_dtype = triton_to_np_dtype(datatype)
        if np_dtype is None:
            raise TpuSharedMemoryException(f"unknown datatype '{datatype}'")
    else:
        np_dtype = np.dtype(datatype)
    if np_dtype == np.dtype(object):
        return deserialize_bytes_tensor(bytes(shm_handle.buf(offset))).reshape(
            shape
        )
    count = num_elements(shape)
    view = shm_handle.buf(offset, count * np_dtype.itemsize)
    return np.frombuffer(view, dtype=np_dtype).reshape(shape)


def as_shared_memory_tensor(
    shm_handle: TpuSharedMemoryRegion, datatype, shape: List[int], offset: int = 0
) -> SharedMemoryTensor:
    """A DLPack-exporting tensor view over the region (zero-copy import
    into torch/numpy; reference cuda_shared_memory/__init__.py:391-399)."""
    if isinstance(datatype, str):
        np_dtype = triton_to_np_dtype(datatype)
        if np_dtype is None or np_dtype == np.dtype(object):
            raise TpuSharedMemoryException(
                f"datatype '{datatype}' cannot be viewed as a DLPack tensor"
            )
    else:
        np_dtype = np.dtype(datatype)
    count = num_elements(shape)
    view = shm_handle.buf(offset, count * np_dtype.itemsize)
    return SharedMemoryTensor(view, shape, np_dtype)


def as_jax_array(
    shm_handle: TpuSharedMemoryRegion,
    datatype,
    shape: List[int],
    offset: int = 0,
    device=None,
):
    """Import region contents as a jax.Array on ``device`` (one H2D DMA)."""
    import jax

    host = get_contents_as_numpy(shm_handle, datatype, shape, offset)
    return jax.device_put(host, device)


def allocated_shared_memory_regions() -> List[str]:
    """Names of TPU regions currently allocated by this process."""
    with _allocated_lock:
        return list(_allocated_regions.keys())


def destroy_shared_memory_region(shm_handle: TpuSharedMemoryRegion) -> None:
    """Free the region (unmap + unlink the backing shm file)."""
    with _allocated_lock:
        _allocated_regions.pop(shm_handle.name(), None)
    shm_handle._destroy()


# Fixed-layout slot ring over one region (PR-11 small-tensor fast path);
# imported late: ring.py pulls helpers from this module at call time.
from client_tpu.utils.tpu_shared_memory.ring import (  # noqa: E402
    ShmRing,
    ShmRingError,
)
