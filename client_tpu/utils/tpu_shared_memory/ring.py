"""Fixed-layout shared-memory ring: the zero-round-trip small-tensor plane.

The named-region shm path (``create_shared_memory_region`` +
``register_tpu_shared_memory`` + per-input ``shared_memory_region``
parameters) amortizes *registration* but still pays per-request costs
that swamp the copy savings at small tensor sizes: per-tensor parameter
maps on the wire, per-request region lookups, and a response that must
round-trip output staging through the same machinery — at r05 the shm
path was *slower* than inline gRPC on add_sub (12,237 vs 13,549
infer/sec, BENCH_r05). The ring closes that gap with ONE pre-registered
region laid out as fixed-size slots:

* the client packs a whole request's tensors into a free slot (name/
  dtype/shape/data framing, one memcpy per tensor) and sends a request
  whose only payload is three integers of parameters
  (``shm_ring_region``/``shm_ring_slot``/``shm_ring_seq``);
* the server reads the slot zero-copy, runs the model, writes the
  response tensors back into the *same* slot, and answers with a slim
  acknowledgement — no tensor bytes cross the wire in either direction;
* a per-slot sequence number + state word make torn writes, stale
  retries, and double-completions detectable instead of corrupting.

Region layout (all little-endian)::

    header (64 B): magic "TPURING1" | version u32 | slot_size u32 |
                   n_slots u32 | reserved
    slot[i] at 64 + i*slot_size:
        state u32 (0 free, 1 request, 2 busy, 3 response, 4 error)
        seq u32   (client-incremented per use; echoed in the request)
        payload_len u32 | reserved u32
        payload (slot_size - 16 bytes):
            n_tensors u32, then per tensor:
                name_len u16 | name | dtype_len u8 | dtype |
                ndim u8 | ndim * i64 shape | data_len u32 | data

The framing is shared verbatim by the server side
(:mod:`client_tpu.server.shm_ring`), so client and server can never
drift on the byte layout.
"""

import struct
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
    np_to_triton_dtype,
)

MAGIC = b"TPURING1"
VERSION = 1
HEADER_SIZE = 64
SLOT_HEADER_SIZE = 16

STATE_FREE = 0
STATE_REQUEST = 1
STATE_BUSY = 2
STATE_RESPONSE = 3
STATE_ERROR = 4

PARAM_REGION = "shm_ring_region"
PARAM_SLOT = "shm_ring_slot"
PARAM_SEQ = "shm_ring_seq"
PARAM_BYTES = "shm_ring_bytes"

_HEADER = struct.Struct("<8sIII")
_SLOT_HEADER = struct.Struct("<IIII")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")


class ShmRingError(InferenceServerException):
    """Client-side ring protocol violation."""


def write_region_header(buf, slot_size: int, n_slots: int) -> None:
    """Stamp the ring header into a freshly allocated region."""
    buf[:HEADER_SIZE] = b"\x00" * HEADER_SIZE
    _HEADER.pack_into(buf, 0, MAGIC, VERSION, slot_size, n_slots)


def read_region_header(buf) -> Tuple[int, int]:
    """Validate the header; returns (slot_size, n_slots)."""
    if len(buf) < HEADER_SIZE:
        raise ShmRingError(
            f"shm ring region is {len(buf)} bytes; too small for the "
            f"{HEADER_SIZE}-byte ring header"
        )
    magic, version, slot_size, n_slots = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ShmRingError(
            "shm ring region has no TPURING1 header (not a ring, or a "
            "torn header write)"
        )
    if version != VERSION:
        raise ShmRingError(
            f"shm ring version {version} is not supported (want {VERSION})"
        )
    if slot_size <= SLOT_HEADER_SIZE or n_slots <= 0:
        raise ShmRingError(
            f"shm ring header is malformed: slot_size {slot_size}, "
            f"n_slots {n_slots}"
        )
    if HEADER_SIZE + slot_size * n_slots > len(buf):
        raise ShmRingError(
            f"shm ring header declares {n_slots} x {slot_size} B slots "
            f"but the region holds only {len(buf)} bytes"
        )
    return slot_size, n_slots


def slot_offset(slot: int, slot_size: int) -> int:
    return HEADER_SIZE + slot * slot_size


def pack_tensors(
    payload: "memoryview", tensors: Sequence[Tuple[str, np.ndarray]]
) -> int:
    """Write the tensor framing into a slot payload view; returns the
    payload length in bytes. Raises when the slot is too small."""
    capacity = len(payload)
    pos = 4
    count = 0
    for name, arr in tensors:
        arr = np.asarray(arr)
        if arr.dtype == np.dtype(object) or arr.dtype.kind in ("S", "U"):
            datatype = "BYTES"
            data = serialize_byte_tensor(arr).tobytes()
        else:
            datatype = np_to_triton_dtype(arr.dtype)
            if datatype is None:
                raise ShmRingError(
                    f"unsupported dtype {arr.dtype} for ring tensor '{name}'"
                )
            data = np.ascontiguousarray(arr)
        name_b = name.encode("utf-8")
        dtype_b = datatype.encode("utf-8")
        shape = arr.shape
        nbytes = data.nbytes if isinstance(data, np.ndarray) else len(data)
        need = 2 + len(name_b) + 1 + len(dtype_b) + 1 + 8 * len(shape) + 4 + nbytes
        if pos + need > capacity:
            raise ShmRingError(
                f"ring slot too small: request needs {pos + need} bytes, "
                f"slot payload holds {capacity}"
            )
        _U16.pack_into(payload, pos, len(name_b))
        pos += 2
        payload[pos : pos + len(name_b)] = name_b
        pos += len(name_b)
        payload[pos] = len(dtype_b)
        pos += 1
        payload[pos : pos + len(dtype_b)] = dtype_b
        pos += len(dtype_b)
        payload[pos] = len(shape)
        pos += 1
        for dim in shape:
            _I64.pack_into(payload, pos, dim)
            pos += 8
        _U32.pack_into(payload, pos, nbytes)
        pos += 4
        if isinstance(data, np.ndarray):
            payload[pos : pos + nbytes] = data.reshape(-1).view(np.uint8)
        else:
            payload[pos : pos + nbytes] = data
        pos += nbytes
        count += 1
    _U32.pack_into(payload, 0, count)
    return pos


def unpack_tensors(
    payload: "memoryview", payload_len: int
) -> List[Tuple[str, str, List[int], "memoryview"]]:
    """Read the tensor framing from a slot payload view; returns
    (name, datatype, shape, data view) per tensor — data stays a
    zero-copy view into the mapping."""
    if payload_len < 4 or payload_len > len(payload):
        raise ShmRingError(
            f"ring payload length {payload_len} is out of bounds "
            f"(payload capacity {len(payload)})"
        )
    (count,) = _U32.unpack_from(payload, 0)
    pos = 4
    tensors = []
    try:
        for _ in range(count):
            (name_len,) = _U16.unpack_from(payload, pos)
            pos += 2
            name = bytes(payload[pos : pos + name_len]).decode("utf-8")
            pos += name_len
            dtype_len = payload[pos]
            pos += 1
            datatype = bytes(payload[pos : pos + dtype_len]).decode("utf-8")
            pos += dtype_len
            ndim = payload[pos]
            pos += 1
            shape = []
            for _ in range(ndim):
                shape.append(_I64.unpack_from(payload, pos)[0])
                pos += 8
            (nbytes,) = _U32.unpack_from(payload, pos)
            pos += 4
            if pos + nbytes > payload_len:
                raise ShmRingError(
                    f"ring tensor '{name}' data ({nbytes} B at {pos}) "
                    f"exceeds the declared payload ({payload_len} B): "
                    "torn or stale slot write"
                )
            tensors.append((name, datatype, shape, payload[pos : pos + nbytes]))
            pos += nbytes
    except (struct.error, IndexError, UnicodeDecodeError):
        raise ShmRingError(
            "ring slot framing is truncated: torn or stale slot write"
        ) from None
    return tensors


def view_as_numpy(datatype: str, shape: List[int], data: "memoryview") -> np.ndarray:
    """Tensor view helper shared by both ends (zero-copy except BYTES)."""
    if datatype == "BYTES":
        return deserialize_bytes_tensor(bytes(data)).reshape(shape)
    np_dtype = triton_to_np_dtype(datatype)
    if np_dtype is None:
        raise ShmRingError(f"unknown ring tensor datatype '{datatype}'")
    return np.frombuffer(data, dtype=np_dtype).reshape(shape)


class RingTicket:
    """One staged request: a claimed slot + its sequence number."""

    __slots__ = ("slot", "seq", "parameters")

    def __init__(self, slot: int, seq: int, region_name: str):
        self.slot = slot
        self.seq = seq
        self.parameters = {
            PARAM_REGION: region_name,
            PARAM_SLOT: slot,
            PARAM_SEQ: seq,
        }


class ShmRing:
    """Client side of the slot ring over one TPU shared-memory region.

    Create once, register once (``register(client)`` /
    ``await aregister(client)``), then per request::

        ticket = ring.stage([("INPUT0", arr0), ("INPUT1", arr1)])
        result = client.infer("simple", [], parameters=ticket.parameters)
        outputs = ring.take_response(ticket)   # {name: ndarray}
        ring.release(ticket)

    ``stage`` blocks (up to ``acquire_timeout_s``) when every slot is in
    flight. Thread-safe; one asyncio loop or N threads can share a ring
    as long as each ticket is released exactly once.
    """

    def __init__(
        self,
        n_slots: int = 32,
        slot_size: int = 8192,
        name: Optional[str] = None,
        device_id: int = 0,
        acquire_timeout_s: float = 30.0,
    ):
        from client_tpu.utils import tpu_shared_memory as tpushm

        if n_slots <= 0 or slot_size <= SLOT_HEADER_SIZE:
            raise ShmRingError(
                f"bad ring geometry: {n_slots} slots x {slot_size} B"
            )
        self.n_slots = n_slots
        self.slot_size = slot_size
        # uuid, not id(): forked workers constructing a ring at the same
        # code point can land on identical heap addresses, and a name
        # collision fails the second worker's registration outright
        self.region_name = name or f"ctpu_ring_{uuid.uuid4().hex[:16]}"
        self._acquire_timeout_s = acquire_timeout_s
        total = HEADER_SIZE + n_slots * slot_size
        self._handle = tpushm.create_shared_memory_region(
            self.region_name, total, device_id
        )
        self._buf = self._handle.buf(0, total)
        write_region_header(self._buf, slot_size, n_slots)
        self._lock = threading.Lock()
        self._free_cv = threading.Condition(self._lock)
        self._free = list(range(n_slots - 1, -1, -1))  # pop() -> slot 0 first
        self._seqs = [0] * n_slots
        self._staged = 0  # lifetime staged-request counter (wraparound test)

    # -- registration --------------------------------------------------------

    def raw_handle(self) -> bytes:
        from client_tpu.utils import tpu_shared_memory as tpushm

        return tpushm.get_raw_handle(self._handle)

    def byte_size(self) -> int:
        return self._handle.byte_size()

    def register(self, client) -> None:
        """Register the backing region with a sync protocol client."""
        client.register_tpu_shared_memory(
            self.region_name,
            self.raw_handle(),
            self._handle.device_id(),
            self.byte_size(),
        )

    async def aregister(self, client) -> None:
        """Register the backing region with an asyncio protocol client."""
        await client.register_tpu_shared_memory(
            self.region_name,
            self.raw_handle(),
            self._handle.device_id(),
            self.byte_size(),
        )

    # -- slot lifecycle ------------------------------------------------------

    def _slot_view(self, slot: int) -> "memoryview":
        off = slot_offset(slot, self.slot_size)
        return self._buf[off : off + self.slot_size]

    def stage(self, inputs: Sequence[Tuple[str, np.ndarray]]) -> RingTicket:
        """Claim a free slot and pack ``inputs`` into it."""
        with self._free_cv:
            if not self._free and not self._free_cv.wait_for(
                lambda: bool(self._free), timeout=self._acquire_timeout_s
            ):
                raise ShmRingError(
                    f"no free ring slot after {self._acquire_timeout_s}s "
                    f"({self.n_slots} slots, all in flight)"
                )
            slot = self._free.pop()
            self._seqs[slot] = seq = (self._seqs[slot] + 1) & 0xFFFFFFFF
            self._staged += 1
        view = self._slot_view(slot)
        payload = view[SLOT_HEADER_SIZE:]
        try:
            payload_len = pack_tensors(payload, inputs)
        except Exception:
            self.release(RingTicket(slot, seq, self.region_name))
            raise
        _SLOT_HEADER.pack_into(view, 0, STATE_REQUEST, seq, payload_len, 0)
        return RingTicket(slot, seq, self.region_name)

    def take_response(
        self, ticket: RingTicket, copy: bool = True
    ) -> Dict[str, np.ndarray]:
        """Read the server's response tensors out of the ticket's slot.

        With ``copy=False`` the arrays are views into the mapping and
        are valid only until :meth:`release`."""
        view = self._slot_view(ticket.slot)
        state, seq, payload_len, _ = _SLOT_HEADER.unpack_from(view, 0)
        if state != STATE_RESPONSE or seq != ticket.seq:
            raise ShmRingError(
                f"ring slot {ticket.slot} has no response for seq "
                f"{ticket.seq} (state {state}, slot seq {seq})"
            )
        outputs: Dict[str, np.ndarray] = {}
        for name, datatype, shape, data in unpack_tensors(
            view[SLOT_HEADER_SIZE:], payload_len
        ):
            arr = view_as_numpy(datatype, shape, data)
            outputs[name] = arr.copy() if copy else arr
        return outputs

    def release(self, ticket: RingTicket) -> None:
        """Return the ticket's slot to the free pool."""
        view = self._slot_view(ticket.slot)
        _SLOT_HEADER.pack_into(view, 0, STATE_FREE, ticket.seq, 0, 0)
        with self._free_cv:
            if ticket.slot not in self._free:
                self._free.append(ticket.slot)
                self._free_cv.notify()

    @property
    def staged_total(self) -> int:
        return self._staged

    # -- convenience ---------------------------------------------------------

    def infer(
        self,
        client,
        model_name: str,
        inputs: Sequence[Tuple[str, np.ndarray]],
        model_version: str = "",
        request_id: str = "",
        parameters: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, np.ndarray]:
        """One ring inference through a sync protocol client.

        Outputs are COPIES (the slot is released before returning). For
        zero-copy reads use the staged API — ``stage`` / send /
        ``take_response(..., copy=False)`` / ``release`` — and release
        only after you are done with the views."""
        ticket = self.stage(inputs)
        try:
            params = dict(parameters or {})
            params.update(ticket.parameters)
            client.infer(
                model_name,
                [],
                model_version=model_version,
                request_id=request_id,
                parameters=params,
            )
            return self.take_response(ticket, copy=True)
        finally:
            self.release(ticket)

    async def ainfer(
        self,
        client,
        model_name: str,
        inputs: Sequence[Tuple[str, np.ndarray]],
        model_version: str = "",
        request_id: str = "",
        parameters: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, np.ndarray]:
        """One ring inference through an asyncio protocol client.
        Outputs are COPIES — see :meth:`infer` for the zero-copy path."""
        ticket = self.stage(inputs)
        try:
            params = dict(parameters or {})
            params.update(ticket.parameters)
            await client.infer(
                model_name,
                [],
                model_version=model_version,
                request_id=request_id,
                parameters=params,
            )
            return self.take_response(ticket, copy=True)
        finally:
            self.release(ticket)

    def close(self) -> None:
        """Free the backing region (unregister with the server first)."""
        from client_tpu.utils import tpu_shared_memory as tpushm

        self._buf = None
        tpushm.destroy_shared_memory_region(self._handle)
