"""System (POSIX) shared-memory utilities.

Capability parity with the reference module
(reference src/python/library/tritonclient/utils/shared_memory/__init__.py
backed by the C extension libcshm.so,
reference .../shared_memory/shared_memory.cc:76-149). Implemented directly
on Linux /dev/shm + mmap — no C extension needed for correctness; the hot
data path (bulk np copies into the mapping) is already zero-Python-loop.
"""

import mmap
import os
import threading
from typing import Dict, List, Optional

import numpy as np

from client_tpu.utils import serialize_byte_tensor

SHM_DIR = "/dev/shm"

_mapped_lock = threading.Lock()
_mapped_regions: Dict[str, "SharedMemoryRegion"] = {}


class SharedMemoryException(Exception):
    """Exception raised for shared-memory errors (errno-style messages)."""

    def __init__(self, err: str):
        self.err = err
        super().__init__(err)

    def __str__(self) -> str:
        return self.err


class SharedMemoryRegion:
    """Handle to a created/attached system shared-memory region."""

    def __init__(
        self,
        triton_shm_name: str,
        shm_key: str,
        fd: int,
        mapping: mmap.mmap,
        byte_size: int,
        offset: int,
        owner: bool,
    ):
        self._triton_shm_name = triton_shm_name
        self._shm_key = shm_key
        self._fd = fd
        self._map = mapping
        self._byte_size = byte_size
        self._offset = offset
        self._owner = owner
        self._closed = False

    # accessor surface matching the reference handle tuple
    def name(self) -> str:
        return self._triton_shm_name

    def key(self) -> str:
        return self._shm_key

    def byte_size(self) -> int:
        return self._byte_size

    def offset(self) -> int:
        return self._offset

    def buf(self, offset: int = 0, length: Optional[int] = None) -> memoryview:
        """A writable memoryview over [offset, offset+length) of the region."""
        if self._closed:
            raise SharedMemoryException(
                "unable to access destroyed shared memory region"
            )
        start = self._offset + offset
        if length is None:
            end = self._offset + self._byte_size
        else:
            end = start + length
        if offset < 0 or end > self._offset + self._byte_size:
            raise SharedMemoryException(
                "unable to access shared memory region beyond its size"
            )
        return memoryview(self._map)[start:end]

    def _close(self, unlink: bool) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._map.close()
        except BufferError:
            # Zero-copy numpy views still reference the mapping; it will be
            # unmapped when the last view is garbage-collected. The fd and
            # (below) the name are released now, matching the reference's
            # unlink-first semantics.
            pass
        finally:
            os.close(self._fd)
        if unlink and self._owner:
            try:
                os.unlink(os.path.join(SHM_DIR, self._shm_key.lstrip("/")))
            except FileNotFoundError:
                pass


def _shm_path(shm_key: str) -> str:
    return os.path.join(SHM_DIR, shm_key.lstrip("/"))


def create_shared_memory_region(
    triton_shm_name: str,
    shm_key: str,
    byte_size: int,
    create_only: bool = False,
) -> SharedMemoryRegion:
    """Create (or attach to) a system shared-memory region.

    Mirrors the reference contract (reference shared_memory/__init__.py:93):
    ``create_only=True`` fails if the key already exists; otherwise an
    existing region is attached and grown to ``byte_size`` if needed.
    """
    if byte_size < 0:
        raise SharedMemoryException(
            "unable to create shared memory region: negative byte_size"
        )
    path = _shm_path(shm_key)
    flags = os.O_RDWR | os.O_CREAT
    if create_only:
        flags |= os.O_EXCL
    try:
        fd = os.open(path, flags, 0o600)
    except FileExistsError:
        raise SharedMemoryException(
            f"unable to create the shared memory region, already exists: "
            f"'{shm_key}'"
        ) from None
    except OSError as e:
        raise SharedMemoryException(
            f"unable to create the shared memory region: {e}"
        ) from None
    try:
        existing = os.fstat(fd).st_size
        if existing < byte_size:
            os.ftruncate(fd, byte_size)
        mapping = mmap.mmap(fd, max(byte_size, existing) or 1)
    except OSError as e:
        os.close(fd)
        raise SharedMemoryException(
            f"unable to map the shared memory region: {e}"
        ) from None
    region = SharedMemoryRegion(
        triton_shm_name, shm_key, fd, mapping, byte_size, 0, owner=True
    )
    with _mapped_lock:
        _mapped_regions[triton_shm_name] = region
    return region


def set_shared_memory_region(
    shm_handle: SharedMemoryRegion, input_values, offset: int = 0
) -> None:
    """Copy a list of numpy arrays into the region back-to-back.

    BYTES (object/str) tensors are written in their serialized wire form,
    matching the reference behavior.
    """
    if not isinstance(input_values, (list, tuple)):
        raise SharedMemoryException(
            "input_values must be a list/tuple of numpy arrays"
        )
    cursor = offset
    for arr in input_values:
        arr = np.asarray(arr)
        if arr.dtype == np.dtype(object) or arr.dtype.kind in ("S", "U"):
            payload = serialize_byte_tensor(arr).tobytes()
        else:
            payload = np.ascontiguousarray(arr).tobytes()
        view = shm_handle.buf(cursor, len(payload))
        view[:] = payload
        cursor += len(payload)


def get_contents_as_numpy(
    shm_handle: SharedMemoryRegion,
    datatype,
    shape: List[int],
    offset: int = 0,
) -> np.ndarray:
    """View the region contents as a numpy array of ``datatype``/``shape``.

    Fixed-size dtypes return a zero-copy view; BYTES deserializes.
    """
    from client_tpu.utils import deserialize_bytes_tensor, num_elements

    dtype = np.dtype(datatype) if not isinstance(datatype, np.dtype) else datatype
    if dtype == np.dtype(object):
        view = shm_handle.buf(offset)
        return deserialize_bytes_tensor(bytes(view)).reshape(shape)
    count = num_elements(shape)
    view = shm_handle.buf(offset, count * dtype.itemsize)
    return np.frombuffer(view, dtype=dtype).reshape(shape)


def mapped_shared_memory_regions() -> List[str]:
    """Names of regions currently mapped by this process."""
    with _mapped_lock:
        return list(_mapped_regions.keys())


def destroy_shared_memory_region(shm_handle: SharedMemoryRegion) -> None:
    """Unmap and unlink the region."""
    with _mapped_lock:
        _mapped_regions.pop(shm_handle.name(), None)
    shm_handle._close(unlink=True)
