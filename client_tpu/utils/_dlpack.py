"""Pure-ctypes DLPack producer/consumer.

Lets shared-memory regions interoperate zero-copy with any DLPack-speaking
framework (torch, jax, numpy >= 1.22) without importing them. Structures
follow the public DLPack v0.8 ABI (dlpack/dlpack.h); same role as the
reference's ctypes implementation
(reference src/python/library/tritonclient/utils/_dlpack.py:57-271).
"""

import ctypes
from typing import Any, Tuple

import numpy as np

# -- DLPack ABI --------------------------------------------------------------

kDLCPU = 1
kDLCUDA = 2
kDLCUDAHost = 3
kDLOpenCL = 4
kDLVulkan = 7
kDLMetal = 8
kDLVPI = 9
kDLROCM = 10
kDLROCMHost = 11
kDLExtDev = 12
kDLCUDAManaged = 13
kDLOneAPI = 14

kDLInt = 0
kDLUInt = 1
kDLFloat = 2
kDLOpaqueHandle = 3
kDLBfloat = 4
kDLComplex = 5
kDLBool = 6


class DLDevice(ctypes.Structure):
    _fields_ = [
        ("device_type", ctypes.c_int32),
        ("device_id", ctypes.c_int32),
    ]


class DLDataType(ctypes.Structure):
    _fields_ = [
        ("type_code", ctypes.c_uint8),
        ("bits", ctypes.c_uint8),
        ("lanes", ctypes.c_uint16),
    ]


class DLTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("device", DLDevice),
        ("ndim", ctypes.c_int32),
        ("dtype", DLDataType),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("strides", ctypes.POINTER(ctypes.c_int64)),
        ("byte_offset", ctypes.c_uint64),
    ]


class DLManagedTensor(ctypes.Structure):
    pass


_DELETER_FN = ctypes.CFUNCTYPE(None, ctypes.POINTER(DLManagedTensor))

DLManagedTensor._fields_ = [
    ("dl_tensor", DLTensor),
    ("manager_ctx", ctypes.c_void_p),
    ("deleter", _DELETER_FN),
]

_CAPSULE_NAME = b"dltensor"
_USED_CAPSULE_NAME = b"used_dltensor"

_pycapi = ctypes.pythonapi
_pycapi.PyCapsule_New.restype = ctypes.py_object
_pycapi.PyCapsule_New.argtypes = [
    ctypes.c_void_p,
    ctypes.c_char_p,
    ctypes.c_void_p,
]
_pycapi.PyCapsule_GetPointer.restype = ctypes.c_void_p
_pycapi.PyCapsule_GetPointer.argtypes = [ctypes.py_object, ctypes.c_char_p]
_pycapi.PyCapsule_IsValid.restype = ctypes.c_int
_pycapi.PyCapsule_IsValid.argtypes = [ctypes.py_object, ctypes.c_char_p]
_pycapi.PyCapsule_SetName.restype = ctypes.c_int
_pycapi.PyCapsule_SetName.argtypes = [ctypes.py_object, ctypes.c_char_p]


def _np_dtype_to_dl(dtype: np.dtype) -> DLDataType:
    try:
        import ml_dtypes

        if dtype == np.dtype(ml_dtypes.bfloat16):
            return DLDataType(kDLBfloat, 16, 1)
    except ImportError:  # pragma: no cover
        pass
    kind_map = {"i": kDLInt, "u": kDLUInt, "f": kDLFloat, "b": kDLBool}
    if dtype.kind not in kind_map:
        raise ValueError(f"dtype {dtype} has no DLPack representation")
    return DLDataType(kind_map[dtype.kind], dtype.itemsize * 8, 1)


def _dl_to_np_dtype(dl: DLDataType) -> np.dtype:
    if dl.lanes != 1:
        raise ValueError("vectorized (lanes>1) DLPack dtypes not supported")
    if dl.type_code == kDLBfloat and dl.bits == 16:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    code_map = {kDLInt: "i", kDLUInt: "u", kDLFloat: "f", kDLBool: "b"}
    if dl.type_code not in code_map:
        raise ValueError(f"DLPack type code {dl.type_code} not supported")
    if dl.type_code == kDLBool:
        return np.dtype(np.bool_)
    return np.dtype(f"{code_map[dl.type_code]}{dl.bits // 8}")


class _Holder:
    """Keeps the backing buffer + ctypes arrays alive until the consumer
    calls the deleter."""

    live = {}

    def __init__(self, owner: Any, managed: DLManagedTensor, shape_arr, deleter):
        self.owner = owner
        self.managed = managed
        self.shape_arr = shape_arr
        self.deleter = deleter


@_DELETER_FN
def _deleter(managed_ptr):
    _Holder.live.pop(ctypes.addressof(managed_ptr.contents), None)


def make_dlpack_capsule(buffer, shape, np_dtype, writable: bool = True):
    """Produce a ``dltensor`` capsule over ``buffer`` (memoryview/ndarray).

    The capsule holds a reference to ``buffer`` until consumed+deleted, so
    the shared-memory mapping stays alive while the importing framework
    uses it.
    """
    arr = np.frombuffer(buffer, dtype=np_dtype).reshape(shape)
    data_ptr = arr.ctypes.data if hasattr(arr, "ctypes") else None
    ndim = arr.ndim
    shape_arr = (ctypes.c_int64 * ndim)(*arr.shape)

    managed = DLManagedTensor()
    managed.dl_tensor.data = ctypes.c_void_p(data_ptr)
    managed.dl_tensor.device = DLDevice(kDLCPU, 0)
    managed.dl_tensor.ndim = ndim
    managed.dl_tensor.dtype = _np_dtype_to_dl(np.dtype(np_dtype))
    managed.dl_tensor.shape = shape_arr
    managed.dl_tensor.strides = None  # compact row-major
    managed.dl_tensor.byte_offset = 0
    managed.manager_ctx = None
    managed.deleter = _deleter

    holder = _Holder(arr, managed, shape_arr, _deleter)
    _Holder.live[ctypes.addressof(managed)] = holder
    return _pycapi.PyCapsule_New(
        ctypes.byref(managed), _CAPSULE_NAME, None
    )


def consume_dlpack_capsule(capsule) -> np.ndarray:
    """Import a ``dltensor`` capsule as a (possibly zero-copy) CPU ndarray.

    Only compact row-major CPU tensors import zero-copy; strided tensors
    are copied; device tensors are rejected (the caller should export to
    host first, e.g. via ``np.asarray`` / ``jax.device_get``).
    """
    if not _pycapi.PyCapsule_IsValid(capsule, _CAPSULE_NAME):
        raise ValueError("expected a 'dltensor' capsule (already consumed?)")
    ptr = _pycapi.PyCapsule_GetPointer(capsule, _CAPSULE_NAME)
    managed = ctypes.cast(ptr, ctypes.POINTER(DLManagedTensor)).contents
    dl = managed.dl_tensor
    if dl.device.device_type not in (kDLCPU, kDLCUDAHost, kDLROCMHost):
        raise ValueError(
            "only host-memory DLPack tensors can be consumed here; stage "
            "device tensors to host first"
        )
    np_dtype = _dl_to_np_dtype(dl.dtype)
    shape = [dl.shape[i] for i in range(dl.ndim)]
    count = int(np.prod(shape)) if shape else 1

    base = dl.data  # ctypes exposes c_void_p struct fields as int/None
    if not base:
        arr = np.empty(shape, dtype=np_dtype)
    else:
        src = (ctypes.c_uint8 * (count * np_dtype.itemsize)).from_address(
            base + dl.byte_offset
        )
        flat = np.frombuffer(src, dtype=np_dtype)
        if dl.strides:
            strides = [dl.strides[i] for i in range(dl.ndim)]
            itemstrides = [s * np_dtype.itemsize for s in strides]
            arr = np.lib.stride_tricks.as_strided(
                flat, shape=shape, strides=itemstrides
            ).copy()
        else:
            arr = flat.reshape(shape).copy()
    # Hand ownership back to the producer.
    if managed.deleter:
        managed.deleter(ctypes.cast(ptr, ctypes.POINTER(DLManagedTensor)))
    _pycapi.PyCapsule_SetName(capsule, _USED_CAPSULE_NAME)
    return arr


def get_dlpack_device(tensor) -> Tuple[int, int]:
    """The (device_type, device_id) a tensor's __dlpack__ would report."""
    if hasattr(tensor, "__dlpack_device__"):
        return tuple(tensor.__dlpack_device__())
    return (kDLCPU, 0)


def is_contiguous_data(ndim, shape_ptr, strides_ptr) -> bool:
    """True if a DLTensor's strides describe compact row-major data."""
    if not strides_ptr:
        return True
    expected = 1
    for i in range(ndim - 1, -1, -1):
        if shape_ptr[i] != 1 and strides_ptr[i] != expected:
            return False
        expected *= shape_ptr[i]
    return True


class SharedMemoryTensor:
    """DLPack-exporting view over a shared-memory buffer.

    Implements ``__dlpack__``/``__dlpack_device__`` so
    ``torch.from_dlpack``/``np.from_dlpack`` import the region zero-copy
    (reference utils/_shared_memory_tensor.py:34-87 semantics).
    """

    def __init__(self, buffer, shape, np_dtype):
        self._buffer = buffer
        self._shape = tuple(shape)
        self._np_dtype = np.dtype(np_dtype)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._np_dtype

    def __dlpack__(self, stream=None, max_version=None, dl_device=None, copy=None):
        return make_dlpack_capsule(self._buffer, self._shape, self._np_dtype)

    def __dlpack_device__(self) -> Tuple[int, int]:
        return (kDLCPU, 0)
