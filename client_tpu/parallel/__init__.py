"""Parallelism utilities for the JAX serving runtime.

The reference is a serving client with no intra-model parallelism
(SURVEY.md §2.7); the models it benchmarks get their parallelism from the
server. In client_tpu the server-side compute path is in-repo, so the
SPMD machinery lives here:

- :func:`create_mesh` — build a ``jax.sharding.Mesh`` over dp/tp/sp axes;
- :mod:`client_tpu.parallel.ring_attention` — ring attention over the
  sequence-parallel axis (long-context prefill);
- :mod:`client_tpu.parallel.sharding` — the declare-and-validate layer
  serving models use (``model.mesh`` dict -> :class:`MeshSpec` ->
  :class:`MeshPlan` with per-tensor ``NamedSharding``\\ s);
- :mod:`client_tpu.parallel.executor` — :class:`ShardedExecutor`, the
  device_put/run/gather seam the server executes sharded models through;
- spec helpers for parameter/activation sharding.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from client_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from client_tpu.parallel.sharding import (  # noqa: F401
    MeshDeclarationError,
    MeshPlan,
    MeshSpec,
    MeshUnavailableError,
    plan_for_model,
)
from client_tpu.parallel.executor import ShardedExecutor  # noqa: F401

DP_AXIS = "dp"  # data parallel (batch)
TP_AXIS = "tp"  # tensor parallel (heads / hidden)
SP_AXIS = "sp"  # sequence parallel (context length)


def create_mesh(
    dp: int = 1,
    tp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``Mesh`` with (dp, tp, sp) axes over ``devices``.

    ``dp*tp*sp`` must equal the device count. Axis order puts tp innermost
    so tensor-parallel collectives ride the fastest ICI links on TPU
    topologies.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp * tp * sp != n:
        raise ValueError(
            f"mesh {dp}x{sp}x{tp} (dp*sp*tp={dp * sp * tp}) does not match "
            f"device count {n}"
        )
    grid = np.asarray(devices).reshape(dp, sp, tp)
    return Mesh(grid, (DP_AXIS, SP_AXIS, TP_AXIS))


def shard(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding helper: ``shard(mesh, 'dp', None)``."""
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
