"""Ring attention: exact attention over a sequence-parallel device axis.

Long-context prefill support for the serving runtime: Q/K/V are sharded
along the sequence dimension across the ``sp`` mesh axis; each device
computes flash-style online-softmax partial attention against its local K/V
block, then rotates K/V around the ring with ``ppermute`` until every query
block has seen every key block. Communication rides the ICI ring and
overlaps with the per-block matmuls that XLA schedules on the MXU.

This is the TPU-native answer to the long-context requirement the reference
delegates to its server (SURVEY.md §5 "long-context / sequence
parallelism"): blockwise ring attention (Liu et al., 2023) expressed with
``shard_map`` + XLA collectives rather than NCCL kernels.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _pvary(x, axis_names):
    """Mark a constant as varying over ``axis_names`` (jax>=0.9 shard_map
    typing: scan carries must match the varying-axes type of the body's
    outputs)."""
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(axis_names))
    if hasattr(jax.lax, "pcast"):  # pragma: no cover - jax variants
        return jax.lax.pcast(x, tuple(axis_names), to="varying")
    return x  # pragma: no cover - older jax has no vma typing


def _local_ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    mesh_axis_names,
    causal: bool,
    scale: float,
):
    """Per-shard body: q/k/v are the local blocks [B, H, L_blk, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    axis_index = jax.lax.axis_index(axis_name)
    batch, heads, q_len, head_dim = q.shape
    k_len = k.shape[2]

    q_positions = axis_index * q_len + jnp.arange(q_len)  # global positions

    def step(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        # Which global block currently sits on this device: blocks rotate
        # "backwards" around the ring, so after i hops we hold the block
        # that started (axis_index - i) mod axis_size.
        src_block = (axis_index - i) % axis_size

        scores = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_cur, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            k_positions = src_block * k_len + jnp.arange(k_len)
            mask = q_positions[:, None] >= k_positions[None, :]
            scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)

        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.exp(scores - m_new[..., None])
        correction = jnp.exp(m_acc - m_new)
        l_new = l_acc * correction + jnp.sum(p, axis=-1)
        o_new = o_acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(p.dtype)
        )

        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = _pvary(
        jnp.zeros((batch, heads, q_len, head_dim), dtype=jnp.float32),
        mesh_axis_names,
    )
    m0 = _pvary(
        jnp.full((batch, heads, q_len), NEG_INF, dtype=jnp.float32),
        mesh_axis_names,
    )
    l0 = _pvary(
        jnp.zeros((batch, heads, q_len), dtype=jnp.float32), mesh_axis_names
    )
    (o_final, _, l_final, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(axis_size)
    )
    # Fully-masked rows (can't happen with causal self-attention, but guard
    # division) and normalization.
    denom = jnp.where(l_final == 0.0, 1.0, l_final)
    return (o_final / denom[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    sp_axis: str = "sp",
):
    """Exact multi-head attention with sequence-parallel ring communication.

    Args
    ----
    q, k, v:
        [batch, heads, seq, head_dim] arrays; ``seq`` is (logically) sharded
        over ``sp_axis``, batch over ``dp_axis``, heads over ``tp_axis``.
    mesh:
        The device mesh holding those axes.
    causal:
        Apply a causal mask using *global* sequence positions.

    Returns [batch, heads, seq, head_dim] with the same sharding as ``q``.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(dp_axis, tp_axis, sp_axis, None)
    body = functools.partial(
        _local_ring_attention,
        axis_name=sp_axis,
        mesh_axis_names=mesh.axis_names,
        causal=causal,
        scale=scale,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


def reference_attention(q, k, v, causal: bool = True, scale=None):
    """Single-device exact attention for testing ring_attention."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_len, k_len = q.shape[2], k.shape[2]
        mask = jnp.arange(q_len)[:, None] >= jnp.arange(k_len)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v.astype(weights.dtype)).astype(
        q.dtype
    )
