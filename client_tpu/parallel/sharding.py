"""Declare-and-validate layer for sharded (multi-device) serving.

A repository model opts into multi-device execution by declaring a mesh
on the class (the serving twin of the ``param_specs`` convention the
model zoo already follows):

    class MyModel(Model):
        mesh = {
            "axes": {"dp": 2, "tp": 2},           # ordered; dp*tp devices
            "inputs": {"INPUT_IDS": ["dp", None]},  # PartitionSpec per input
            "outputs": {"EMBEDDING": ["dp", None]},
        }

At load/warmup time the declaration is parsed into a :class:`MeshSpec`
(pure validation, no devices touched) and resolved against
``jax.devices()`` into a :class:`MeshPlan` — a live ``jax.sharding.Mesh``
plus ``NamedSharding`` per declared input/output. Resolution failures are
*load* failures with operator-grade reasons ("mesh requires 4 devices,
host has 1"), surfaced through the repository index per the lifecycle
semantics (state UNAVAILABLE, reason ``load failed: ...``) instead of a
500 at first infer.

Spec entries follow ``jax.sharding.PartitionSpec``: each element of an
input/output spec is ``None`` (replicated dim), an axis name, or a list
of axis names (a dim sharded over multiple mesh axes).
"""

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

SpecEntry = Any  # None | str | tuple of str (post-validation)


class MeshDeclarationError(ValueError):
    """The model's ``mesh`` declaration is malformed (a config bug —
    distinct from :class:`MeshUnavailableError`, which is a property of
    the host the model landed on)."""


class MeshUnavailableError(ValueError):
    """The declared mesh cannot be built on this host. ``str(exc)`` is
    the canonical operator-facing reason (``"mesh requires N devices,
    host has M"``) that rides into the repository index verbatim."""


def _validate_spec(
    name: str, spec: Any, axes: Dict[str, int], kind: str
) -> Tuple[SpecEntry, ...]:
    """One input/output PartitionSpec declaration -> normalized tuple."""
    if not isinstance(spec, (list, tuple)):
        raise MeshDeclarationError(
            f"mesh {kind} spec for '{name}' must be a list of dims "
            f"(got {type(spec).__name__})"
        )
    normalized: List[SpecEntry] = []
    for dim, entry in enumerate(spec):
        if entry is None:
            normalized.append(None)
            continue
        parts = entry if isinstance(entry, (list, tuple)) else (entry,)
        for axis in parts:
            if not isinstance(axis, str) or axis not in axes:
                raise MeshDeclarationError(
                    f"mesh {kind} spec for '{name}' dim {dim} names "
                    f"unknown axis {axis!r} (declared axes: "
                    f"{sorted(axes)})"
                )
        normalized.append(
            tuple(parts) if isinstance(entry, (list, tuple)) else entry
        )
    return tuple(normalized)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A validated mesh declaration (no devices touched yet).

    ``axes`` preserves declaration order — it becomes the mesh's axis
    order, so the declaring model controls which axis rides the
    fastest ICI links (innermost last, per ``create_mesh``'s convention).
    """

    axes: Tuple[Tuple[str, int], ...]
    inputs: Dict[str, Tuple[SpecEntry, ...]]
    outputs: Dict[str, Tuple[SpecEntry, ...]]

    @property
    def device_count(self) -> int:
        n = 1
        for _name, size in self.axes:
            n *= size
        return n

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return dict(self.axes)

    @staticmethod
    def parse(declaration: Any) -> "MeshSpec":
        """Validate a raw ``model.mesh`` dict; raises
        :class:`MeshDeclarationError` with the first problem found."""
        if not isinstance(declaration, dict):
            raise MeshDeclarationError(
                f"mesh declaration must be a dict, got "
                f"{type(declaration).__name__}"
            )
        axes_raw = declaration.get("axes")
        if not isinstance(axes_raw, dict) or not axes_raw:
            raise MeshDeclarationError(
                "mesh declaration needs a non-empty 'axes' dict "
                '(e.g. {"axes": {"dp": 2, "tp": 2}})'
            )
        axes: List[Tuple[str, int]] = []
        for name, size in axes_raw.items():
            if not isinstance(name, str) or not name:
                raise MeshDeclarationError(
                    f"mesh axis names must be strings, got {name!r}"
                )
            if isinstance(size, bool) or not isinstance(size, int) or size < 1:
                raise MeshDeclarationError(
                    f"mesh axis '{name}' size must be a positive int, "
                    f"got {size!r}"
                )
            axes.append((name, size))
        axis_sizes = dict(axes)
        unknown = set(declaration) - {"axes", "inputs", "outputs"}
        if unknown:
            raise MeshDeclarationError(
                f"unknown mesh declaration key(s): {sorted(unknown)}"
            )
        inputs = {
            name: _validate_spec(name, spec, axis_sizes, "input")
            for name, spec in (declaration.get("inputs") or {}).items()
        }
        outputs = {
            name: _validate_spec(name, spec, axis_sizes, "output")
            for name, spec in (declaration.get("outputs") or {}).items()
        }
        return MeshSpec(axes=tuple(axes), inputs=inputs, outputs=outputs)


@dataclasses.dataclass
class MeshPlan:
    """A :class:`MeshSpec` resolved against live devices: the mesh, the
    per-tensor ``NamedSharding``s, and the topology description the
    metadata/debug surfaces serve."""

    spec: MeshSpec
    mesh: Any  # jax.sharding.Mesh
    devices: Tuple[Any, ...]  # the jax devices backing the mesh
    input_shardings: Dict[str, Any]  # name -> NamedSharding
    output_shardings: Dict[str, Any]
    #: pod topology: how many OS processes the mesh's devices span (1 for
    #: every pre-pod mesh) and how many of its devices this process holds
    process_count: int = 1
    local_device_count: int = -1  # -1: single-process, all devices local

    @property
    def spans_processes(self) -> bool:
        """True when the mesh crosses process boundaries — collectives
        ride jax.distributed and per-process shards are non-addressable
        from any one member."""
        return self.process_count > 1

    @property
    def device_labels(self) -> Tuple[str, ...]:
        """Stable per-device metric/debug labels (the jax device ids)."""
        return tuple(str(d.id) for d in self.devices)

    def replicated(self):
        """NamedSharding replicating a tensor over the whole mesh."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def sharding(self, *spec_entries):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(*spec_entries))

    def batch_multiple(self, name: str) -> int:
        """The divisibility requirement the executor pads an input's
        leading (batch) dim to: the product of axis sizes sharding dim 0
        (1 when dim 0 is replicated or the input is undeclared)."""
        spec = self.spec.inputs.get(name)
        if not spec or spec[0] is None:
            return 1
        parts = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
        sizes = self.spec.axis_sizes
        multiple = 1
        for axis in parts:
            multiple *= sizes[axis]
        return multiple

    def describe(self) -> Dict[str, Any]:
        """The topology block metadata/debug surfaces serve: axes, the
        device ids the model occupies, and the declared shardings."""

        def _spec_doc(spec: Tuple[SpecEntry, ...]) -> List[Any]:
            return [
                list(entry) if isinstance(entry, tuple) else entry
                for entry in spec
            ]

        local = self.local_device_count
        if local < 0:
            local = len(self.devices)
        return {
            "axes": {name: size for name, size in self.spec.axes},
            "device_count": len(self.devices),
            "devices": [d.id for d in self.devices],
            "process_count": self.process_count,
            "local_device_count": local,
            "spans_processes": self.spans_processes,
            "inputs": {
                name: _spec_doc(spec)
                for name, spec in self.spec.inputs.items()
            },
            "outputs": {
                name: _spec_doc(spec)
                for name, spec in self.spec.outputs.items()
            },
        }


def resolve(spec: MeshSpec, devices: Optional[Sequence] = None) -> MeshPlan:
    """Build the live :class:`MeshPlan` for ``spec`` over ``devices``
    (default ``jax.devices()``). Raises :class:`MeshUnavailableError`
    with the canonical reason when the host has too few devices."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    if devices is None:
        devices = jax.devices()  # GLOBAL device list under jax.distributed
    needed = spec.device_count
    if len(devices) < needed:
        # canonical single-process reason (pinned by tests/operators);
        # pod members append their topology so "host has 2" is readable
        # as "2 of the pod's devices live here"
        msg = f"mesh requires {needed} devices, host has {len(devices)}"
        try:
            process_count = int(jax.process_count())
        except Exception:  # noqa: BLE001 - backend not initialized
            process_count = 1
        if process_count > 1:
            msg += (
                f" (pod of {process_count} processes, "
                f"{len(jax.local_devices())} devices local to this one)"
            )
        raise MeshUnavailableError(msg)
    used = tuple(devices[:needed])
    names = tuple(name for name, _size in spec.axes)
    sizes = tuple(size for _name, size in spec.axes)
    mesh = Mesh(np.asarray(used).reshape(sizes), names)

    def _sharding(entries: Tuple[SpecEntry, ...]) -> NamedSharding:
        return NamedSharding(mesh, PartitionSpec(*entries))

    # pod topology of the devices actually used: a mesh spans processes
    # exactly when its device slice does, regardless of the host's total
    try:
        this_process = int(jax.process_index())
    except Exception:  # noqa: BLE001 - backend not initialized
        this_process = 0
    owners = {getattr(d, "process_index", 0) for d in used}
    local_count = sum(
        1 for d in used if getattr(d, "process_index", 0) == this_process
    )
    return MeshPlan(
        spec=spec,
        mesh=mesh,
        devices=used,
        input_shardings={
            name: _sharding(entries) for name, entries in spec.inputs.items()
        },
        output_shardings={
            name: _sharding(entries) for name, entries in spec.outputs.items()
        },
        process_count=max(1, len(owners)),
        local_device_count=local_count,
    )


def plan_for_model(model, devices: Optional[Sequence] = None) -> Optional[MeshPlan]:
    """Resolve a repository model's ``mesh`` declaration (None when the
    model declares none). Raises :class:`InferenceServerException` —
    which the repository load path records as ``load failed: <reason>``
    — on a malformed declaration or an unsatisfiable mesh, so a model
    that cannot execute is UNAVAILABLE at load time, never a 500 at
    first infer."""
    declaration = getattr(model, "mesh", None)
    if declaration is None:
        return None
    from client_tpu.utils import InferenceServerException

    try:
        spec = MeshSpec.parse(declaration)
        return resolve(spec, devices)
    except (MeshDeclarationError, MeshUnavailableError) as e:
        raise InferenceServerException(str(e)) from e
