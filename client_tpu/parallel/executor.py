"""ShardedExecutor: the device-placement half of sharded serving.

One instance per loaded sharded model (built in ``warmup()`` next to the
jit-compiled callable). Per execution it:

1. pads each input's leading (batch) dim to the mesh's divisibility
   requirement (a batch of 1 on a ``dp=2`` mesh pads to 2 — the padded
   rows compute garbage the gather step slices back off);
2. ``jax.device_put``\\ s each input onto its declared ``NamedSharding``
   (undeclared inputs replicate over the mesh), so the compiled callable
   never pays an implicit host->device transfer inside the traced
   program;
3. runs the jit-compiled sharded callable under the mesh;
4. gathers the outputs back to host numpy with one batched
   ``jax.device_get`` (addressable-shard reads) and trims the padding.

Above this seam a sharded model is indistinguishable from a plain one:
``execute()`` still maps name->ndarray to name->ndarray, so every
ServerCore execution path (batcher, direct, single-async, decoupled)
serves it unchanged.

Phase timings (device_put / compute / gather) accumulate on the executor
— the numbers PERF.md's device_put/gather-cost note and the
``debug_state()`` devices block report. The clock is injectable
(``clock_ns``), matching the repo's clock-lint rules for this package.
"""

import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from client_tpu.parallel.sharding import MeshPlan


def place_global(array: Any, sharding: Any) -> Any:
    """Place a host array onto a sharding that may span processes.

    ``jax.device_put`` only accepts fully-addressable shardings; on a
    process-spanning mesh each process instead builds the global array
    from the shards it owns (``make_array_from_callback`` — every pod
    member calls this with the SAME host value, which is exactly the
    lockstep contract the step bus enforces)."""
    import jax

    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(array, sharding)
    array = np.asarray(array)
    return jax.make_array_from_callback(
        array.shape, sharding, lambda index: array[index]
    )


def gather_global(value: Any) -> np.ndarray:
    """Read a device array back to host numpy, whether or not every
    shard is addressable from this process. Non-addressable arrays ride
    ``process_allgather`` (a collective — every pod member must call)."""
    import jax

    if getattr(value, "is_fully_addressable", True):
        return np.asarray(jax.device_get(value))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(value, tiled=True))


class ShardedExecutor:
    """Runs ``fn`` (a dict->dict jitted callable) under a resolved
    :class:`~client_tpu.parallel.sharding.MeshPlan`.

    Parameters
    ----------
    plan:
        The resolved mesh + per-tensor shardings.
    fn:
        ``fn(inputs: Dict[str, jax.Array]) -> Dict[str, jax.Array]`` —
        typically a closure over device-placed params, jit-compiled by
        the model's ``warmup()``.
    clock_ns:
        Injectable monotonic clock (fake-clock tests).
    """

    def __init__(
        self,
        plan: MeshPlan,
        fn: Callable[[Dict[str, Any]], Dict[str, Any]],
        clock_ns: Callable[[], int] = time.monotonic_ns,
    ):
        self.plan = plan
        self._fn = fn
        self._clock_ns = clock_ns
        self._lock = threading.Lock()
        self._executions = 0
        self._device_put_ns = 0
        self._compute_ns = 0
        self._gather_ns = 0

    # -- placement ----------------------------------------------------------

    def _place(self, inputs: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """device_put every input onto its declared sharding (replicated
        when undeclared), padding batch dims to the mesh multiple."""
        plan = self.plan
        placed: Dict[str, Any] = {}
        replicated = None
        for name, array in inputs.items():
            sharding = plan.input_shardings.get(name)
            if sharding is None:
                if replicated is None:
                    replicated = plan.replicated()
                sharding = replicated
            else:
                multiple = plan.batch_multiple(name)
                if multiple > 1 and array.shape[0] % multiple:
                    pad = multiple - array.shape[0] % multiple
                    array = np.concatenate(
                        [
                            array,
                            np.zeros(
                                (pad,) + array.shape[1:], dtype=array.dtype
                            ),
                        ]
                    )
            placed[name] = place_global(array, sharding)
        return placed

    # -- execution ----------------------------------------------------------

    def __call__(
        self, inputs: Dict[str, np.ndarray], rows: Optional[int] = None
    ) -> Dict[str, np.ndarray]:
        """One sharded execution. ``rows`` (default: the leading dim of
        the first input) is the true batch size outputs are trimmed to
        after the gather — padding added by :meth:`_place` never reaches
        the wire."""
        import jax

        if rows is None:
            rows = next(
                (int(a.shape[0]) for a in inputs.values() if a.ndim), 0
            )
        t0 = self._clock_ns()
        placed = self._place(inputs)
        t1 = self._clock_ns()
        with self.plan.mesh:
            raw = self._fn(placed)
        raw = jax.block_until_ready(raw)
        t2 = self._clock_ns()
        outputs: Dict[str, np.ndarray] = {}
        for name, value in raw.items():
            array = gather_global(value)
            if (
                rows
                and array.ndim
                and name in self.plan.output_shardings
                and array.shape[0] > rows
            ):
                array = array[:rows]
            outputs[name] = array
        t3 = self._clock_ns()
        with self._lock:
            self._executions += 1
            self._device_put_ns += t1 - t0
            self._compute_ns += t2 - t1
            self._gather_ns += t3 - t2
        return outputs

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative phase accounting: how much of the sharded path's
        wall time is placement vs compute vs readback (the
        device_put/gather-cost methodology in PERF.md)."""
        with self._lock:
            return {
                "executions": self._executions,
                "device_put_ns": self._device_put_ns,
                "compute_ns": self._compute_ns,
                "gather_ns": self._gather_ns,
            }
