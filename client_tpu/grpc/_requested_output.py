"""InferRequestedOutput for the gRPC protocol.

Capability parity with reference
src/python/library/tritonclient/grpc/_requested_output.py.
"""

from client_tpu.grpc._generated import grpc_service_pb2 as pb


class InferRequestedOutput:
    """Describes a requested output tensor for a gRPC inference request."""

    def __init__(self, name: str, class_count: int = 0):
        self._output = pb.ModelInferRequest.InferRequestedOutputTensor(name=name)
        if class_count != 0:
            self._output.parameters["classification"].int64_param = int(
                class_count
            )

    def name(self) -> str:
        return self._output.name

    def set_shared_memory(
        self, region_name: str, byte_size: int, offset: int = 0
    ) -> "InferRequestedOutput":
        """Direct the server to write this output into a registered region."""
        self._output.parameters["shared_memory_region"].string_param = region_name
        self._output.parameters["shared_memory_byte_size"].int64_param = int(
            byte_size
        )
        if offset != 0:
            self._output.parameters["shared_memory_offset"].int64_param = int(
                offset
            )
        return self

    def unset_shared_memory(self) -> "InferRequestedOutput":
        self._output.parameters.pop("shared_memory_region", None)
        self._output.parameters.pop("shared_memory_byte_size", None)
        self._output.parameters.pop("shared_memory_offset", None)
        return self

    def _get_tensor(self) -> pb.ModelInferRequest.InferRequestedOutputTensor:
        return self._output
