"""Asyncio gRPC client for KServe v2 inference servers.

Mirrors the sync surface of :mod:`client_tpu.grpc` with coroutines, plus
``stream_infer`` — an async-iterator interface over the decoupled
bidirectional stream with cancellation (reference
src/python/library/tritonclient/grpc/aio/__init__.py:50-798, ``stream_infer``
at :688, cancel at :798).
"""

import asyncio
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence, Union

import grpc

from client_tpu._client import InferenceServerClientBase
from client_tpu._request import Request
from client_tpu.grpc import (
    MAX_GRPC_MESSAGE_SIZE,
    KeepAliveOptions,
    _grpc_compression,
    _to_json,
)
from client_tpu.grpc._generated import grpc_service_pb2 as service_pb2
from client_tpu.grpc._infer_input import InferInput
from client_tpu.grpc._infer_result import InferResult
from client_tpu.grpc._requested_output import InferRequestedOutput
from client_tpu.grpc._service_stubs import GRPCInferenceServiceStub
from client_tpu.grpc._utils import (
    get_inference_request,
    is_sequence_request as _is_sequence_request,
    request_is_hedgeable,
    request_routing_key,
    rpc_error_to_exception,
)
from client_tpu.lifecycle import (
    EndpointPool,
    failover_retry_policy,
    grpc_status_is_endpoint_outage,
    hedged_send_async,
    resolve_hedge_policy,
    status_is_unavailable,
)
from client_tpu.observability.trace import (
    NOOP_TRACE,
    TRACEPARENT_HEADER,
    Tracer,
    start_trace,
)
from client_tpu.resilience import (
    CircuitBreaker,
    RetryPolicy,
    run_with_resilience_async,
    sequence_is_idempotent,
)
from client_tpu.utils import InferenceServerException

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "KeepAliveOptions",
]


class InferenceServerClient(InferenceServerClientBase):
    """Asyncio client for the KServe v2 gRPC protocol."""

    def __init__(
        self,
        url=None,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional[grpc.ChannelCredentials] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[List] = None,
        retry_policy: Optional[RetryPolicy] = None,
        circuit_breaker: Optional[CircuitBreaker] = None,
        tracer: Optional[Tracer] = None,
        urls=None,
        endpoint_cooldown_s: float = 1.0,
        logger=None,
        stream_mode: bool = False,
        routing_policy=None,
        hedge_policy=None,
    ):
        """``url`` may be a single ``host:port``, a comma list, or an
        :class:`~client_tpu.lifecycle.EndpointPool`; ``urls=[...]`` names
        replica endpoints. One channel per endpoint (created lazily);
        unary RPCs route per ``routing_policy`` — sticky primary by
        default, or ``round_robin`` / ``least_outstanding`` / ``p2c`` /
        ``consistent_hash`` (affinity on the ``routing_key`` request
        parameter) — and fail over, immediately, no backoff sleep, when
        an endpoint answers UNAVAILABLE or the connection dies;
        recovering endpoints must pass a ``ServerReady`` probe first.
        ``stream_infer`` binds to the endpoint current at stream open.

        ``hedge_policy`` (seconds, ``"p95"``, or a
        :class:`~client_tpu.lifecycle.HedgePolicy`) arms request
        hedging: an idempotent infer that outlives the hedge delay
        launches one duplicate on a different endpoint, first response
        wins, the loser is cancelled without touching telemetry or retry
        counts. Sequence requests and requests carrying shm-ring tickets
        never hedge.

        ``stream_mode=True`` routes every unary :meth:`infer` over one
        long-lived multiplexed ``ModelStreamInfer`` stream (correlation
        ids, concurrent server-side execution), amortizing per-RPC setup
        — the small-request fast path. Requests with explicit
        ``request_id`` must keep them unique while in flight. The stream
        pins one endpoint, so routing policies and hedging apply only at
        (re)open, not per request."""
        super().__init__()
        self._verbose = verbose
        self._stream_mode = stream_mode
        self._mux = None
        self._pool = EndpointPool.resolve(
            url,
            urls,
            cooldown_s=endpoint_cooldown_s,
            logger=logger,
            routing_policy=routing_policy,
        )
        self._hedge = resolve_hedge_policy(hedge_policy)
        if self._pool.size > 1 and retry_policy is None:
            retry_policy = failover_retry_policy(self._pool.size)
        self._retry_policy = retry_policy
        self._circuit_breaker = circuit_breaker
        self._tracer = tracer
        if channel_args is not None:
            options = list(channel_args)
        else:
            options = [
                ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
                ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
                ("grpc.primary_user_agent", "client-tpu-grpc-aio"),
            ]
            if keepalive_options is not None:
                options += [
                    ("grpc.keepalive_time_ms", keepalive_options.keepalive_time_ms),
                    (
                        "grpc.keepalive_timeout_ms",
                        keepalive_options.keepalive_timeout_ms,
                    ),
                    (
                        "grpc.keepalive_permit_without_calls",
                        int(keepalive_options.keepalive_permit_without_calls),
                    ),
                    (
                        "grpc.http2.max_pings_without_data",
                        keepalive_options.http2_max_pings_without_data,
                    ),
                ]
        self._channel_options = options
        if creds is not None:
            self._credentials: Optional[grpc.ChannelCredentials] = creds
        elif ssl:

            def _read(path):
                if path is None:
                    return None
                with open(path, "rb") as f:
                    return f.read()

            self._credentials = grpc.ssl_channel_credentials(
                root_certificates=_read(root_certificates),
                private_key=_read(private_key),
                certificate_chain=_read(certificate_chain),
            )
        else:
            self._credentials = None
        self._channels: Dict[str, grpc.aio.Channel] = {}
        self._stubs: Dict[str, GRPCInferenceServiceStub] = {}
        # live stream_infer iterators whose endpoint pin is still open
        # (close() releases any a caller abandoned without cancelling)
        self._pinned_stream_iterators = set()
        # primary-bound aliases (stream_infer uses them)
        self._channel = self._channel_for(self._pool.urls[0])
        self._client_stub = self._stub_for(self._pool.urls[0])

    def _channel_for(self, url: str) -> "grpc.aio.Channel":
        channel = self._channels.get(url)
        if channel is None:
            if self._credentials is not None:
                channel = grpc.aio.secure_channel(
                    url, self._credentials, options=self._channel_options
                )
            else:
                channel = grpc.aio.insecure_channel(
                    url, options=self._channel_options
                )
            self._channels[url] = channel
        return channel

    def _stub_for(self, url: str) -> GRPCInferenceServiceStub:
        stub = self._stubs.get(url)
        if stub is None:
            stub = GRPCInferenceServiceStub(self._channel_for(url))
            self._stubs[url] = stub
        return stub

    async def _probe_endpoint(self, endpoint, timeout: float = 1.0) -> bool:
        """ServerReady against a specific endpoint (the gRPC face of the
        /v2/health/ready check the pool demands of recovering members)."""
        try:
            response = await self._stub_for(endpoint.url).ServerReady(
                service_pb2.ServerReadyRequest(), timeout=timeout
            )
            return bool(response.ready)
        except grpc.RpcError:
            return False

    async def _pick_endpoint(
        self,
        budget_s: Optional[float] = None,
        exclude=None,
        key=None,
    ):
        """Pool choice for the next attempt; recovering endpoints pass a
        ServerReady probe first, budgeted against the attempt timeout.
        ``exclude`` asks for an endpoint other than the one given (the
        hedge path); ``key`` is the consistent-hash routing key."""
        pool = self._pool
        probe_timeout = 1.0
        if budget_s:
            probe_timeout = min(1.0, max(0.05, budget_s / pool.size))
        for _ in range(pool.size):
            endpoint = pool.pick(key=key, exclude=exclude)
            if not pool.needs_probe(endpoint):
                return endpoint
            if await self._probe_endpoint(endpoint, timeout=probe_timeout):
                pool.mark_up(endpoint)
                return endpoint
            pool.mark_down(endpoint)
        return pool.pick(key=key, exclude=exclude)

    def _metadata(self, headers: Optional[Dict[str, str]]):
        request = Request(headers or {})
        self._call_plugin(request)
        return tuple((k.lower(), v) for k, v in request.headers.items()) or None

    async def _call(
        self,
        name,
        request,
        headers=None,
        client_timeout=None,
        compression=None,
        idempotent=True,
        probe=False,
        trace=NOOP_TRACE,
        routing_key=None,
        hedgeable=True,
    ):
        """One RPC under the retry/deadline/breaker rules.

        ``client_timeout`` is the total budget across attempts; each
        attempt's gRPC timeout is derived from what remains of it.
        ``probe`` marks liveness/readiness checks: single attempt, no
        breaker accounting (a probe reports current state; its failures
        during a restart must not poison a shared breaker). An active
        ``trace`` records one "request" span per attempt.
        ``routing_key`` feeds consistent-hash affinity; ``hedgeable``
        (with the client's hedge policy armed and ``idempotent``) lets
        the attempt launch a tail hedge on a second endpoint.
        """
        metadata = self._metadata(headers)
        if probe:
            try:
                return await getattr(
                    self._stub_for(self._pool.pick().url), name
                )(
                    request,
                    metadata=metadata,
                    timeout=client_timeout,
                    compression=compression,
                )
            except grpc.RpcError as e:
                raise rpc_error_to_exception(e) from None
        pool = self._pool

        async def _raw_send(endpoint, attempt_timeout):
            # one attempt against a SPECIFIC endpoint; pool begin/finish
            # bracketing belongs to the caller (plain or hedged)
            try:
                value = await getattr(self._stub_for(endpoint.url), name)(
                    request,
                    metadata=metadata,
                    timeout=attempt_timeout,
                    compression=compression,
                )
            except grpc.RpcError as e:
                exc = rpc_error_to_exception(e)
                if grpc_status_is_endpoint_outage(exc.status()):
                    # draining/dead endpoint — or a server that CANCELLED
                    # an accepted RPC mid-shutdown (local cancellation
                    # raises CancelledError, never an RpcError): bench
                    # it; with an alternative, skip the backoff and fail
                    # over NOW
                    pool.observe(
                        endpoint, token="StatusCode.UNAVAILABLE"
                    )
                    if pool.has_alternative(endpoint):
                        exc.retry_backoff_cap_s = 0.0
                raise exc from None
            pool.observe(endpoint, ok=True)
            return value

        hedge = self._hedge if (hedgeable and idempotent) else None
        if hedge is not None:

            async def _send(attempt_timeout):
                return await hedged_send_async(
                    pool,
                    hedge,
                    lambda budget, exclude: self._pick_endpoint(
                        budget, exclude=exclude, key=routing_key
                    ),
                    _raw_send,
                    attempt_timeout,
                )

        else:

            async def _send(attempt_timeout):
                endpoint = await self._pick_endpoint(
                    attempt_timeout, key=routing_key
                )
                started = pool.begin(endpoint)
                try:
                    value = await _raw_send(endpoint, attempt_timeout)
                except asyncio.CancelledError:
                    # cancellation says nothing about the endpoint: close
                    # the bracket without booking an error
                    pool.finish(endpoint, started, ok=False, cancelled=True)
                    raise
                except InferenceServerException as e:
                    # the token keeps client-fault codes (INVALID_ARGUMENT
                    # and kin) out of consecutive-error ejection
                    pool.finish(
                        endpoint, started, ok=False, token=e.status()
                    )
                    raise
                except BaseException:
                    # an unwrapped failure: close the bracket so the
                    # outstanding gauge never leaks
                    pool.finish(endpoint, started, ok=False)
                    raise
                pool.finish(endpoint, started, ok=True)
                return value

        return await run_with_resilience_async(
            trace.wrap_attempt_async(_send),
            retry_policy=self._retry_policy,
            circuit_breaker=self._circuit_breaker,
            budget_s=client_timeout,
            idempotent=idempotent,
            description=f"gRPC {name}",
        )

    async def _mux_infer(
        self,
        trace,
        client_timeout,
        idempotent: bool,
        **kwargs,
    ):
        """One multiplexed-stream infer under the retry/breaker rules,
        with per-request endpoint-pool telemetry (the stream pins its
        endpoint at open; every request brackets it)."""
        if self._mux is None:
            from client_tpu.grpc._mux import AioStreamMultiplexer

            self._mux = AioStreamMultiplexer(self)
        mux = self._mux
        pool = self._pool

        async def _send(attempt_timeout):
            mux._ensure_open()
            endpoint = mux.endpoint
            started = pool.begin(endpoint)
            try:
                value = await mux.infer(
                    client_timeout=attempt_timeout, **kwargs
                )
            except InferenceServerException as e:
                pool.finish(endpoint, started, ok=False)
                if status_is_unavailable(e.status()):
                    pool.observe(endpoint, token=e.status())
                    if pool.has_alternative(endpoint):
                        e.retry_backoff_cap_s = 0.0
                raise
            except BaseException:
                pool.finish(endpoint, started, ok=False)
                raise
            pool.finish(endpoint, started, ok=True)
            pool.observe(endpoint, ok=True)
            return value

        return await run_with_resilience_async(
            trace.wrap_attempt_async(_send),
            retry_policy=self._retry_policy,
            circuit_breaker=self._circuit_breaker,
            budget_s=client_timeout,
            idempotent=idempotent,
            description="gRPC mux ModelInfer",
        )

    async def close(self) -> None:
        if self._mux is not None:
            mux, self._mux = self._mux, None
            await mux.close()
        # release pins of stream iterators the caller abandoned without
        # cancelling — the snapshot's pinned_streams must not outlive
        # the client that counted them
        for iterator in list(self._pinned_stream_iterators):
            iterator._unpin()
        for channel in self._channels.values():
            await channel.close()

    def endpoint_snapshot(self) -> dict:
        """Live per-endpoint pool telemetry — outstanding requests, EWMA
        latency, error/reroute counters per endpoint (see
        :meth:`~client_tpu.lifecycle.EndpointPool.snapshot`). Unary
        calls are begin/finish-bracketed; the bidirectional stream pins
        its endpoint at open and is not counted per-request."""
        return self._pool.snapshot()

    async def __aenter__(self) -> "InferenceServerClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- health -------------------------------------------------------------

    async def is_server_live(self, headers=None, client_timeout=None) -> bool:
        r = await self._call(
            "ServerLive",
            service_pb2.ServerLiveRequest(),
            headers,
            client_timeout,
            probe=True,
        )
        return r.live

    async def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        r = await self._call(
            "ServerReady",
            service_pb2.ServerReadyRequest(),
            headers,
            client_timeout,
            probe=True,
        )
        return r.ready

    async def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ) -> bool:
        r = await self._call(
            "ModelReady",
            service_pb2.ModelReadyRequest(name=model_name, version=model_version),
            headers,
            client_timeout,
            probe=True,
        )
        return r.ready

    # -- metadata / config / repository / stats ------------------------------

    async def get_server_metadata(
        self, headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "ServerMetadata",
            service_pb2.ServerMetadataRequest(),
            headers,
            client_timeout,
        )
        return _to_json(r) if as_json else r

    async def get_model_metadata(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelMetadata",
            service_pb2.ModelMetadataRequest(
                name=model_name, version=model_version
            ),
            headers,
            client_timeout,
        )
        return _to_json(r) if as_json else r

    async def get_model_config(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelConfig",
            service_pb2.ModelConfigRequest(name=model_name, version=model_version),
            headers,
            client_timeout,
        )
        return _to_json(r) if as_json else r

    async def get_model_repository_index(
        self, headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "RepositoryIndex",
            service_pb2.RepositoryIndexRequest(),
            headers,
            client_timeout,
        )
        return _to_json(r) if as_json else r

    async def load_model(
        self, model_name, headers=None, config=None, files=None, client_timeout=None
    ) -> None:
        request = service_pb2.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        if files:
            for name, content in files.items():
                request.parameters[name].bytes_param = content
        await self._call(
            "RepositoryModelLoad",
            request,
            headers,
            client_timeout,
            idempotent=False,
        )

    async def unload_model(
        self,
        model_name,
        headers=None,
        unload_dependents=False,
        client_timeout=None,
    ) -> None:
        request = service_pb2.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        await self._call(
            "RepositoryModelUnload",
            request,
            headers,
            client_timeout,
            idempotent=False,
        )

    async def get_inference_statistics(
        self,
        model_name="",
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelStatistics",
            service_pb2.ModelStatisticsRequest(
                name=model_name, version=model_version
            ),
            headers,
            client_timeout,
        )
        return _to_json(r) if as_json else r

    # -- shared memory -------------------------------------------------------

    async def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "SystemSharedMemoryStatus",
            service_pb2.SystemSharedMemoryStatusRequest(name=region_name),
            headers,
            client_timeout,
        )
        return _to_json(r) if as_json else r

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ) -> None:
        await self._call(
            "SystemSharedMemoryRegister",
            service_pb2.SystemSharedMemoryRegisterRequest(
                name=name, key=key, offset=offset, byte_size=byte_size
            ),
            headers,
            client_timeout,
            idempotent=False,
        )

    async def unregister_system_shared_memory(
        self, name="", headers=None, client_timeout=None
    ) -> None:
        await self._call(
            "SystemSharedMemoryUnregister",
            service_pb2.SystemSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
            idempotent=False,
        )

    async def get_tpu_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "TpuSharedMemoryStatus",
            service_pb2.TpuSharedMemoryStatusRequest(name=region_name),
            headers,
            client_timeout,
        )
        return _to_json(r) if as_json else r

    async def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ) -> None:
        await self._call(
            "TpuSharedMemoryRegister",
            service_pb2.TpuSharedMemoryRegisterRequest(
                name=name,
                raw_handle=raw_handle,
                device_id=device_id,
                byte_size=byte_size,
            ),
            headers,
            client_timeout,
            idempotent=False,
        )

    async def unregister_tpu_shared_memory(
        self, name="", headers=None, client_timeout=None
    ) -> None:
        await self._call(
            "TpuSharedMemoryUnregister",
            service_pb2.TpuSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
            idempotent=False,
        )

    # -- inference -----------------------------------------------------------

    @staticmethod
    def prepare_request(
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: Union[int, str] = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        parameters: Optional[Dict[str, Any]] = None,
    ):
        """Build a reusable ``ModelInferRequest`` for :meth:`infer_prepared`.

        The reference reuses the request proto across sends
        (reference grpc_client.cc:1419-1580 PreRunProcessing); building
        once and resending skips per-send input marshalling entirely.
        """
        return get_inference_request(
            model_name,
            inputs,
            model_version=model_version,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )

    async def infer_prepared(
        self,
        request,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
    ) -> InferResult:
        """Send a request built by :meth:`prepare_request` (reusable)."""
        trace = start_trace(
            self._tracer, "infer", surface="grpc", model=request.model_name
        )
        if (
            self._stream_mode
            and headers is None
            and compression_algorithm is None
            # a sampled traceparent must ride per-request metadata, which
            # the long-lived stream cannot carry: traced requests take
            # the unary path so W3C propagation keeps working
            and not trace.traceparent
        ):
            try:
                response = await self._mux_infer(
                    trace,
                    client_timeout,
                    not _is_sequence_request(request),
                    prepared_request=request,
                )
                with trace.stage("deserialize"):
                    result = InferResult(response)
            except BaseException as e:
                trace.finish(error=e)
                raise
            trace.finish()
            return result
        if trace.traceparent:
            headers = {
                **(headers or {}),
                TRACEPARENT_HEADER: trace.traceparent,
            }
        try:
            response = await self._call(
                "ModelInfer",
                request,
                headers,
                client_timeout,
                compression=_grpc_compression(compression_algorithm),
                idempotent=not _is_sequence_request(request),
                trace=trace,
                routing_key=self._request_routing_key(request),
                hedgeable=self._request_hedgeable(request),
            )
            with trace.stage("deserialize"):
                result = InferResult(response)
        except BaseException as e:
            trace.finish(error=e)
            raise
        trace.finish()
        return result

    def _request_routing_key(self, request):
        """The consistent-hash key of a built request, read from the
        policy's key parameter (zero work unless such a policy is on)."""
        return request_routing_key(request, self._pool.key_parameter)

    def _request_hedgeable(self, request) -> bool:
        """Requests referencing single-writer buffers (shm-ring tickets,
        shared-memory regions) never hedge — shared classification in
        :func:`client_tpu.grpc._utils.request_is_hedgeable` (checked
        only while hedging is armed)."""
        return self._hedge is None or request_is_hedgeable(request)

    async def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: Union[int, str] = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
        parameters: Optional[Dict[str, Any]] = None,
    ) -> InferResult:
        trace = start_trace(
            self._tracer, "infer", surface="grpc", model=model_name
        )
        if (
            self._stream_mode
            and headers is None
            and compression_algorithm is None
            # a sampled traceparent must ride per-request metadata, which
            # the long-lived stream cannot carry: traced requests take
            # the unary path so W3C propagation keeps working
            and not trace.traceparent
        ):
            # persistent multiplexed stream: serialization happens inside
            # the mux (protobuf-free builder); per-request headers and
            # compression need the unary path
            try:
                response = await self._mux_infer(
                    trace,
                    client_timeout,
                    sequence_is_idempotent(sequence_id),
                    model_name=model_name,
                    inputs=inputs,
                    model_version=model_version,
                    request_id=request_id,
                    outputs=outputs,
                    parameters=parameters,
                    priority=priority,
                    timeout=timeout,
                    sequence_id=sequence_id,
                    sequence_start=sequence_start,
                    sequence_end=sequence_end,
                )
                with trace.stage("deserialize"):
                    result = InferResult(response)
            except BaseException as e:
                trace.finish(error=e)
                raise
            trace.finish()
            return result
        try:
            with trace.stage("serialize"):
                request = get_inference_request(
                    model_name,
                    inputs,
                    model_version=model_version,
                    request_id=request_id,
                    outputs=outputs,
                    sequence_id=sequence_id,
                    sequence_start=sequence_start,
                    sequence_end=sequence_end,
                    priority=priority,
                    timeout=timeout,
                    parameters=parameters,
                )
            if trace.traceparent:
                headers = {
                    **(headers or {}),
                    TRACEPARENT_HEADER: trace.traceparent,
                }
            response = await self._call(
                "ModelInfer",
                request,
                headers,
                client_timeout,
                compression=_grpc_compression(compression_algorithm),
                idempotent=sequence_is_idempotent(sequence_id),
                trace=trace,
                routing_key=self._request_routing_key(request),
                hedgeable=self._request_hedgeable(request),
            )
            with trace.stage("deserialize"):
                result = InferResult(response)
        except BaseException as e:
            trace.finish(error=e)
            raise
        trace.finish()
        return result

    def stream_infer(
        self,
        inputs_iterator: AsyncIterator[Dict[str, Any]],
        stream_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
    ) -> AsyncIterator:
        """Run inferences over the decoupled bidirectional stream.

        ``inputs_iterator`` yields dicts of :meth:`infer`-style kwargs (at
        minimum ``model_name`` and ``inputs``). Returns an async iterator of
        ``(InferResult, error)`` tuples carrying a ``cancel()`` method.
        """

        async def _request_iterator():
            async for kwargs in inputs_iterator:
                enable_empty_final = kwargs.pop(
                    "enable_empty_final_response", False
                )
                request = get_inference_request(
                    kwargs.pop("model_name"),
                    kwargs.pop("inputs"),
                    **kwargs,
                )
                if enable_empty_final:
                    request.parameters[
                        "triton_enable_empty_final_response"
                    ].bool_param = True
                yield request

        # bound to the pool's current endpoint at open (draining/dead
        # endpoints are routed around; the stream then stays on it).
        # Stream traffic is counted as a PINNED STREAM on the endpoint,
        # not per request: a decoupled request may produce N responses,
        # so there is no per-request begin/finish to bracket — routing
        # policies deliberately exclude pinned-stream load from their
        # signals (snapshot() surfaces the pin count for visibility).
        pool = self._pool
        endpoint = pool.pick()
        call = self._stub_for(endpoint.url).ModelStreamInfer(
            _request_iterator(),
            metadata=self._metadata(headers),
            timeout=stream_timeout,
            compression=_grpc_compression(compression_algorithm),
        )
        pool.pin_stream(endpoint)
        registry = self._pinned_stream_iterators

        class _ResponseIterator:
            """Async iterator of (result, error); cancellable."""

            def __init__(self, grpc_call):
                self._call = grpc_call
                self._pinned = True
                registry.add(self)

            def _unpin(self):
                if self._pinned:
                    self._pinned = False
                    pool.unpin_stream(endpoint)
                    registry.discard(self)

            def cancel(self) -> bool:
                cancelled = self._call.cancel()
                self._unpin()
                return cancelled

            def __aiter__(self):
                return self

            async def __anext__(self):
                try:
                    response = await self._call.read()
                except asyncio.CancelledError:
                    self._unpin()
                    raise StopAsyncIteration from None
                except grpc.RpcError as e:
                    self._unpin()
                    raise rpc_error_to_exception(e) from None
                if response == grpc.aio.EOF:
                    self._unpin()
                    raise StopAsyncIteration
                if response.error_message:
                    return None, InferenceServerException(
                        response.error_message
                    )
                return InferResult(response.infer_response), None

        return _ResponseIterator(call)
