"""InferResult for the gRPC protocol.

Wraps a ModelInferResponse; decodes raw_output_contents (or proto contents)
into numpy/jax arrays. Capability parity with reference
src/python/library/tritonclient/grpc/_infer_result.py.
"""

from typing import Dict, Optional

import numpy as np

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)

_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


class InferResult:
    """The result of a gRPC inference request."""

    def __init__(self, response: pb.ModelInferResponse):
        self._response = response
        self._index: Dict[str, int] = {
            out.name: i for i, out in enumerate(response.outputs)
        }

    def get_response(self, as_json: bool = False):
        """The underlying ModelInferResponse (or a JSON-ish dict)."""
        if as_json:
            from google.protobuf import json_format

            return json_format.MessageToDict(
                self._response, preserving_proto_field_name=True
            )
        return self._response

    def get_output(self, name: str, as_json: bool = False):
        """Metadata for output ``name`` (None if absent)."""
        i = self._index.get(name)
        if i is None:
            return None
        out = self._response.outputs[i]
        if as_json:
            from google.protobuf import json_format

            return json_format.MessageToDict(
                out, preserving_proto_field_name=True
            )
        return out

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        """Output ``name`` as a numpy array (None if absent or in shm)."""
        i = self._index.get(name)
        if i is None:
            return None
        out = self._response.outputs[i]
        shape = list(out.shape)
        datatype = out.datatype
        if "shared_memory_region" in out.parameters:
            return None  # caller reads the registered region directly
        if i < len(self._response.raw_output_contents):
            raw = self._response.raw_output_contents[i]
            if datatype == "BYTES":
                return deserialize_bytes_tensor(raw).reshape(shape)
            np_dtype = triton_to_np_dtype(datatype)
            if np_dtype is None:
                raise InferenceServerException(
                    f"unknown datatype '{datatype}' for output '{name}'"
                )
            return np.frombuffer(raw, dtype=np_dtype).reshape(shape)
        field = _CONTENTS_FIELD.get(datatype)
        if field is not None and out.HasField("contents"):
            values = getattr(out.contents, field)
            if datatype == "BYTES":
                return np.array(list(values), dtype=np.object_).reshape(shape)
            return np.array(
                list(values), dtype=triton_to_np_dtype(datatype)
            ).reshape(shape)
        return None

    def as_jax(self, name: str, device=None):
        """Output ``name`` as a jax.Array placed on ``device``."""
        host = self.as_numpy(name)
        if host is None:
            return None
        if host.dtype == np.dtype(object):
            raise InferenceServerException(
                f"BYTES output '{name}' cannot convert to a jax.Array"
            )
        import jax

        return jax.device_put(host, device)
