"""Bidirectional streaming machinery for the sync gRPC client.

A queue-fed request iterator plus a response-reader thread invoking the
user callback — the same shape as the reference's ``_InferStream`` /
``_RequestIterator`` (reference
src/python/library/tritonclient/grpc/_infer_stream.py:39-190), with the
response-statistics bug class avoided by never assuming 1:1
request/response (decoupled models send 0..N responses per request).
"""

import queue
import threading
from typing import Callable, Optional

import grpc

from client_tpu.grpc._infer_result import InferResult
from client_tpu.grpc._utils import rpc_error_to_exception
from client_tpu.utils import InferenceServerException

_SENTINEL = object()


class _RequestIterator:
    """Blocking iterator feeding the gRPC stream writer."""

    def __init__(self):
        self._queue: "queue.Queue" = queue.Queue()

    def put(self, request) -> None:
        self._queue.put(request)

    def close(self) -> None:
        self._queue.put(_SENTINEL)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is _SENTINEL:
            raise StopIteration
        return item


class InferStream:
    """One active bidirectional inference stream."""

    def __init__(self, callback: Callable, verbose: bool = False):
        self._callback = callback
        self._verbose = verbose
        self._requests = _RequestIterator()
        self._call = None
        self._worker: Optional[threading.Thread] = None
        self._active = False
        self._lock = threading.Lock()

    def init_handler(self, call) -> None:
        """Attach the gRPC call object and start the reader thread."""
        self._call = call
        self._active = True
        self._worker = threading.Thread(
            target=self._process_responses,
            name="client-tpu-grpc-stream",
            daemon=True,
        )
        self._worker.start()

    @property
    def request_iterator(self) -> _RequestIterator:
        return self._requests

    def is_active(self) -> bool:
        with self._lock:
            return self._active

    def enqueue_request(self, request) -> None:
        if not self.is_active():
            raise InferenceServerException(
                "stream is not active; call start_stream() first"
            )
        self._requests.put(request)

    def _deactivate(self) -> None:
        with self._lock:
            self._active = False

    def _process_responses(self) -> None:
        try:
            for response in self._call:
                if self._verbose:
                    print(f"stream response: {response.error_message or 'ok'}")
                if response.error_message:
                    self._callback(
                        None, InferenceServerException(response.error_message)
                    )
                else:
                    self._callback(InferResult(response.infer_response), None)
        except grpc.RpcError as e:
            self._deactivate()
            if e.code() != grpc.StatusCode.CANCELLED:
                self._callback(None, rpc_error_to_exception(e))
        except Exception as e:  # noqa: BLE001 - surface to callback
            self._deactivate()
            self._callback(None, InferenceServerException(str(e)))
        finally:
            self._deactivate()

    def close(self, cancel_requests: bool = False) -> None:
        """End the stream. ``cancel_requests`` aborts in-flight requests."""
        if cancel_requests and self._call is not None:
            self._call.cancel()
        self._requests.close()
        if self._worker is not None:
            self._worker.join(timeout=30)
            if self._worker.is_alive() and self._call is not None:
                # Server never sent the final response: force the reader out
                # so its callback cannot interleave with a later stream.
                self._call.cancel()
                self._worker.join(timeout=10)
        self._deactivate()
