"""Bidirectional streaming machinery for the sync gRPC client.

A queue-fed request iterator plus a response-reader thread invoking the
user callback — the same shape as the reference's ``_InferStream`` /
``_RequestIterator`` (reference
src/python/library/tritonclient/grpc/_infer_stream.py:39-190), with the
response-statistics bug class avoided by never assuming 1:1
request/response (decoupled models send 0..N responses per request).

Resilience: when the owning client carries a ``RetryPolicy``, a stream
torn down with ``UNAVAILABLE`` (server restart, preempted pod) is
reopened with the policy's backoff. Requests that had already been
written to the dead connection are surfaced to the callback as errors —
never silently replayed (a decoupled request is not idempotent);
requests still queued client-side carry over to the new connection
unsent-and-safe.
"""

import queue
import threading
from typing import Callable, Optional

import grpc

from client_tpu.grpc._infer_result import InferResult
from client_tpu.grpc._utils import rpc_error_to_exception
from client_tpu.resilience import Deadline
from client_tpu.utils import InferenceServerException

_SENTINEL = object()


class _RequestIterator:
    """Blocking iterator feeding the gRPC stream writer."""

    def __init__(self, on_send: Optional[Callable] = None):
        self._queue: "queue.Queue" = queue.Queue()
        self._on_send = on_send

    def put(self, request) -> None:
        self._queue.put(request)

    def close(self) -> None:
        self._queue.put(_SENTINEL)

    def drain_pending(self) -> list:
        """Pop everything still queued (unsent requests; used to carry
        them over to a reconnected stream). The sentinel, if queued,
        is preserved in order."""
        items = []
        while True:
            try:
                items.append(self._queue.get_nowait())
            except queue.Empty:
                return items

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is _SENTINEL:
            raise StopIteration
        if self._on_send is not None:
            # the stream writer consumed it: it is now in flight; pass
            # ourselves so the stream can tell live and dead writers apart
            self._on_send(item, self)
        return item


class InferStream:
    """One active bidirectional inference stream."""

    def __init__(
        self,
        callback: Callable,
        verbose: bool = False,
        retry_policy=None,
        stream_budget_s: Optional[float] = None,
    ):
        self._callback = callback
        self._verbose = verbose
        self._retry_policy = retry_policy
        # the caller's stream_timeout is a TOTAL budget: replacement
        # calls opened by reconnects get only what remains of it
        clock = retry_policy.clock if retry_policy is not None else None
        self._deadline = (
            Deadline(stream_budget_s, **({"clock": clock} if clock else {}))
            if stream_budget_s is not None
            else None
        )
        self._requests = _RequestIterator(on_send=self._note_sent)
        self._call = None
        self._reconnect: Optional[Callable] = None
        self._worker: Optional[threading.Thread] = None
        self._active = False
        self._closing = False
        self._lock = threading.Lock()
        # ids of requests written to the wire and not yet answered
        self._inflight: list = []

    def init_handler(self, call, reconnect: Optional[Callable] = None) -> None:
        """Attach the gRPC call object and start the reader thread.

        ``reconnect(request_iterator)`` (optional) opens a replacement
        call after an UNAVAILABLE teardown; reconnection only happens
        when the owning client also configured a retry policy.
        """
        self._call = call
        self._reconnect = reconnect
        self._active = True
        self._worker = threading.Thread(
            target=self._process_responses,
            name="client-tpu-grpc-stream",
            daemon=True,
        )
        self._worker.start()

    @property
    def request_iterator(self) -> _RequestIterator:
        return self._requests

    def is_active(self) -> bool:
        with self._lock:
            return self._active

    def enqueue_request(self, request) -> None:
        if not self.is_active():
            raise InferenceServerException(
                "stream is not active; call start_stream() first"
            )
        # put under the lock: a concurrent reconnect swap must not leave
        # this request stranded on the drained, dead iterator
        with self._lock:
            self._requests.put(request)

    def _deactivate(self) -> None:
        with self._lock:
            self._active = False

    # -- in-flight accounting ------------------------------------------------

    def _note_sent(self, request, iterator) -> None:
        with self._lock:
            if iterator is self._requests:
                self._inflight.append(getattr(request, "id", ""))
                return
            # a dead connection's writer consumed this after the
            # reconnect swap; the call was already torn down, so it was
            # never transmitted — carry it over unsent (safe to send,
            # not a replay). The put stays under the lock: a second
            # reconnect must not retire the target iterator between the
            # staleness check and the put.
            self._requests.put(request)

    def _note_response(self, response) -> None:
        """Retire the in-flight entry a response answers (by id when the
        server echoes one, else the oldest un-id'd entry). Decoupled
        models may send several responses per request; the first retires
        the entry, and later ones must not retire OTHER requests'
        entries — exact accounting for un-id'd decoupled requests is
        inherently approximate, so set ``request_id`` when streaming
        decoupled models under a retry policy."""
        rid = response.infer_response.id
        with self._lock:
            if rid:
                if rid in self._inflight:
                    self._inflight.remove(rid)
            elif "" in self._inflight:
                self._inflight.remove("")

    def _fail_inflight(self) -> None:
        """Surface every unanswered in-flight request as an error.

        A raising user callback must not skip the remaining
        notifications or kill the reader thread mid-teardown."""
        with self._lock:
            lost, self._inflight = self._inflight, []
        for rid in lost:
            label = f"request '{rid}'" if rid else "a request"
            try:
                error = InferenceServerException(
                    f"{label} was in flight when the stream "
                    "disconnected; it was not retried",
                    status="StatusCode.UNAVAILABLE",
                )
                # correlation hook for multiplexed-unary consumers
                # (client_tpu.grpc._mux): which request this error kills
                error.request_id = rid
                self._callback(None, error)
            except Exception:  # noqa: BLE001 - user callback raised
                if self._verbose:
                    print(f"stream callback raised while failing {label}")

    # -- reader --------------------------------------------------------------

    def _swap_iterators(self) -> "_RequestIterator":
        """Replace the request iterator, carrying queued-but-unsent
        requests over. The drain happens under the lock: a concurrent
        ``enqueue_request`` (which also puts under the lock) must land
        AFTER every carried-over request, preserving stream FIFO order.
        From this point the dead connection's writer is 'stale': anything
        it still consumes is carried over by ``_note_sent`` instead of
        silently vanishing."""
        with self._lock:
            old = self._requests
            fresh = _RequestIterator(on_send=self._note_sent)
            for item in old.drain_pending():
                fresh.put(item)
            self._requests = fresh
        # unblock the dead call's writer thread, if it still waits
        old.close()
        return fresh

    def _process_responses(self) -> None:
        # the stream must read inactive once this thread exits, no
        # matter how it exits (including a user callback raising)
        try:
            self._read_loop()
        finally:
            self._deactivate()

    def _read_loop(self) -> None:
        policy = self._retry_policy
        reconnects = 0
        while True:
            try:
                for response in self._call:
                    self._note_response(response)
                    if self._verbose:
                        print(
                            f"stream response: "
                            f"{response.error_message or 'ok'}"
                        )
                    if response.error_message:
                        error = InferenceServerException(
                            response.error_message
                        )
                        # in-band errors echo the request id (when the
                        # client sent one): carry it for mux correlation
                        error.request_id = response.infer_response.id
                        self._callback(None, error)
                    else:
                        self._callback(
                            InferResult(response.infer_response), None
                        )
                    reconnects = 0  # a healthy read resets the budget
                return  # clean end-of-stream
            except grpc.RpcError as e:
                code = e.code()
                if code == grpc.StatusCode.CANCELLED:
                    return
                # in-flight accounting is part of the reconnect feature;
                # without a policy the legacy single-error-callback
                # semantics are preserved exactly
                if policy is not None and self._reconnect is not None:
                    backoff = policy.backoff_s(reconnects)
                    if (
                        code == grpc.StatusCode.UNAVAILABLE
                        and reconnects + 1 < policy.max_attempts
                        and not self._closing
                        and (
                            self._deadline is None
                            # same rule as the unary loop: the remaining
                            # stream budget must cover the backoff, else
                            # the reconnect would open with a floored
                            # timeout and die immediately
                            or self._deadline.remaining_s() > backoff
                        )
                    ):
                        # order matters: retire the dead writer BEFORE
                        # failing in-flight (so late sends surface as
                        # lost), and fail BEFORE the new call starts
                        # writing (so carried-over requests are not
                        # falsely reported lost)
                        fresh = self._swap_iterators()
                        self._fail_inflight()
                        policy.sleep(backoff)
                        reconnects += 1
                        if self._closing:
                            # close() arrived during the backoff: do not
                            # open a fresh connection post-close
                            self._deactivate()
                            self._callback(None, rpc_error_to_exception(e))
                            return
                        remaining = (
                            self._deadline.attempt_timeout_s()
                            if self._deadline is not None
                            else None
                        )
                        try:
                            self._call = self._reconnect(fresh, remaining)
                        except Exception:  # noqa: BLE001 - best-effort
                            pass
                        else:
                            if self._verbose:
                                print(
                                    f"stream reconnected "
                                    f"(attempt {reconnects})"
                                )
                            continue
                    else:
                        # lost with the connection: error, never replay
                        self._fail_inflight()
                self._deactivate()
                self._callback(None, rpc_error_to_exception(e))
                return
            except Exception as e:  # noqa: BLE001 - surface to callback
                # same accounting contract on non-RpcError teardowns:
                # with the reconnect feature engaged, in-flight requests
                # must still be surfaced, never silently dropped
                if policy is not None and self._reconnect is not None:
                    self._fail_inflight()
                self._deactivate()
                self._callback(None, InferenceServerException(str(e)))
                return

    def close(self, cancel_requests: bool = False) -> None:
        """End the stream. ``cancel_requests`` aborts in-flight requests."""
        self._closing = True
        if cancel_requests and self._call is not None:
            self._call.cancel()
        with self._lock:
            requests = self._requests
        requests.close()
        if self._worker is not None:
            self._worker.join(timeout=30)
            if self._worker.is_alive() and self._call is not None:
                # Server never sent the final response: force the reader out
                # so its callback cannot interleave with a later stream.
                self._call.cancel()
                self._worker.join(timeout=10)
        self._deactivate()
