"""Persistent multiplexed inference streams (client side).

Unary ``infer()`` over gRPC pays per-RPC machinery — method resolution,
header blocks, a fresh HTTP/2 stream, a completion queue round-trip —
per request. Stream mode (``InferenceServerClient(stream_mode=True)``)
amortizes all of it: every unary infer rides ONE long-lived
``ModelStreamInfer`` bidi stream as a message pair, correlated by
request id. The server executes multiplexed requests concurrently (the
``multiplex`` request parameter opts each request out of the stream's
in-order guarantee) and responses resolve per-request futures as they
arrive, in any order.

* :class:`AioStreamMultiplexer` — asyncio clients. Requests are
  serialized by the protobuf-free builder in
  :mod:`client_tpu.grpc._wire` (head + tensor-metadata blocks are
  memoized per signature, so the steady state appends raw tensor bytes
  to cached templates); shapes the fast builder declines fall back to
  the proto request builder.
* :class:`SyncStreamMultiplexer` — blocking clients, built on
  :class:`~client_tpu.grpc._infer_stream.InferStream`, which brings the
  PR-1 reconnect machinery: a stream torn down with UNAVAILABLE reopens
  under the client's retry policy, in-flight requests surface as
  retryable errors (never silently replayed), and queued-unsent
  requests carry over.

Request ids: callers may pass their own ``request_id`` (must be unique
among in-flight requests); otherwise the mux stamps ``mx<N>``.
"""

import asyncio
import threading
from typing import Any, Dict, Optional

import grpc

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._utils import (
    get_inference_request,
    rpc_error_to_exception,
)
from client_tpu.utils import InferenceServerException

_STREAM_METHOD = "/inference.GRPCInferenceService/ModelStreamInfer"

# bounded like the server codec's template caches
_CACHE_MAX = 256


def _derive_status(message: str) -> Optional[str]:
    """Status for an in-band stream error. The wire frame carries only
    the message text — without a derived status, a drain rejection or
    queue-full that is RETRYABLE on the unary path (gRPC UNAVAILABLE /
    RESOURCE_EXHAUSTED) would be terminal under stream mode and never
    trigger pool failover. Mirrors the server's message patterns
    (server._grpc_codec.status_code_for) for the retry-relevant codes."""
    lowered = message.lower()
    if "queue" in lowered and "full" in lowered:
        return "StatusCode.RESOURCE_EXHAUSTED"
    if "timed out in queue" in lowered:
        return "StatusCode.DEADLINE_EXCEEDED"
    if (
        "not ready" in lowered
        or "unavailable" in lowered
        or "draining" in lowered
        or "not accepting new inference" in lowered
    ):
        return "StatusCode.UNAVAILABLE"
    return None


def _inband_error(message: str) -> InferenceServerException:
    return InferenceServerException(message, status=_derive_status(message))


class _FastRequestBuilder:
    """Protobuf-free ModelInferRequest serializer with memoized
    head/metadata blocks (the client mirror of the server's encode
    templates). ``build`` returns None for shapes it does not cover —
    the caller falls back to the proto builder."""

    __slots__ = ("_wire", "_head_cache", "_meta_cache")

    def __init__(self):
        from client_tpu.grpc import _wire

        self._wire = _wire
        self._head_cache: Dict[Any, bytes] = {}
        self._meta_cache: Dict[Any, bytes] = {}

    def build(
        self,
        model_name: str,
        inputs,
        model_version: str,
        request_id: str,
        outputs,
        parameters: Optional[Dict[str, Any]],
    ) -> Optional[bytes]:
        wire = self._wire
        raws = []
        sig = []
        for inp in inputs:
            raw = inp._get_raw_content()
            if raw is None:
                return None  # shared-memory/typed-contents input
            raws.append(raw)
            sig.append((inp.name(), inp.datatype(), tuple(inp.shape())))
        out_names = ()
        if outputs:
            for out in outputs:
                tensor = out._get_tensor()
                if tensor.parameters:
                    return None  # classification / shm-ref outputs
            out_names = tuple(out._get_tensor().name for out in outputs)
        head_key = (model_name, model_version)
        head = self._head_cache.get(head_key)
        if head is None:
            if len(self._head_cache) >= _CACHE_MAX:
                self._head_cache.clear()
            head = self._head_cache[head_key] = wire.encode_head(*head_key)
        meta_key = (tuple(sig), out_names)
        meta = self._meta_cache.get(meta_key)
        if meta is None:
            if len(self._meta_cache) >= _CACHE_MAX:
                self._meta_cache.clear()
            meta = self._meta_cache[meta_key] = wire.encode_input_meta_block(
                sig, out_names
            )
        buf = bytearray(head)
        if request_id:
            rid = request_id.encode("utf-8")
            buf.append(0x1A)
            wire.write_varint(buf, len(rid))
            buf += rid
        if parameters:
            wire._encode_params_map(buf, 0x22, parameters)
        buf += meta
        for raw in raws:
            buf.append(0x3A)
            wire.write_varint(buf, len(raw))
            buf += raw
        return bytes(buf)


def _proto_request_bytes(
    model_name,
    inputs,
    model_version,
    request_id,
    outputs,
    parameters,
    priority,
    timeout,
    sequence_id,
    sequence_start,
    sequence_end,
) -> bytes:
    """Fallback: proto request builder + the mux correlation fields."""
    request = get_inference_request(
        model_name,
        inputs,
        model_version=model_version,
        request_id=request_id,
        outputs=outputs,
        sequence_id=sequence_id,
        sequence_start=sequence_start,
        sequence_end=sequence_end,
        priority=priority,
        timeout=timeout,
        parameters=parameters,
    )
    request.parameters["multiplex"].bool_param = True
    return request.SerializeToString()


class AioStreamMultiplexer:
    """One long-lived bidi stream multiplexing unary infers (asyncio).

    Opened lazily on first ``infer``; a dead stream (UNAVAILABLE, server
    restart) fails its in-flight futures with a retryable error and the
    next ``infer`` opens a fresh stream — combined with the client's
    retry policy this is reconnect-on-UNAVAILABLE at the request level.
    """

    def __init__(self, client):
        self._client = client
        self._builder = _FastRequestBuilder()
        self._call = None
        self._reader: Optional[asyncio.Task] = None
        self._pending: Dict[str, asyncio.Future] = {}
        self._counter = 0
        self._write_lock = asyncio.Lock()
        self._methods: Dict[str, Any] = {}
        self.endpoint = None  # pool endpoint pinned at open

    # -- stream lifecycle ----------------------------------------------------

    def _method_for(self, url: str):
        method = self._methods.get(url)
        if method is None:
            channel = self._client._channel_for(url)
            method = self._methods[url] = channel.stream_stream(
                _STREAM_METHOD,
                request_serializer=None,
                response_deserializer=pb.ModelStreamInferResponse.FromString,
            )
        return method

    def _ensure_open(self) -> None:
        if self._call is not None:
            return
        endpoint = self._client._pool.pick()
        self.endpoint = endpoint
        call = self._method_for(endpoint.url)(
            metadata=self._client._metadata(None)
        )
        self._call = call
        self._reader = asyncio.ensure_future(self._read_loop(call))

    async def _read_loop(self, call) -> None:
        try:
            while True:
                response = await call.read()
                if response is grpc.aio.EOF:
                    self._fail_pending(
                        InferenceServerException(
                            "multiplexed stream closed by the server",
                            status="StatusCode.UNAVAILABLE",
                        )
                    )
                    return
                inner = response.infer_response
                if response.error_message and not inner.id:
                    # an error the server could not correlate (the bytes
                    # never decoded): no single waiter owns it — fail
                    # everything retryably rather than hang one forever
                    self._fail_pending(_inband_error(response.error_message))
                    continue
                future = self._pending.pop(inner.id, None)
                if future is None or future.done():
                    continue
                if response.error_message:
                    future.set_exception(
                        _inband_error(response.error_message)
                    )
                else:
                    future.set_result(inner)
        except asyncio.CancelledError:
            self._fail_pending(
                InferenceServerException(
                    "multiplexed stream closed",
                    status="StatusCode.CANCELLED",
                )
            )
            raise
        except grpc.RpcError as e:
            self._fail_pending(rpc_error_to_exception(e))
        except Exception as e:  # noqa: BLE001 - surface to waiters
            self._fail_pending(InferenceServerException(str(e)))
        finally:
            if self._call is call:
                self._call = None
                self._reader = None

    def _fail_pending(self, error: InferenceServerException) -> None:
        pending, self._pending = self._pending, {}
        for future in pending.values():
            if not future.done():
                future.set_exception(error)

    # -- request path --------------------------------------------------------

    def next_id(self) -> str:
        self._counter += 1
        return f"mx{self._counter}"

    async def infer(
        self,
        model_name: str = "",
        inputs=(),
        model_version: str = "",
        request_id: str = "",
        outputs=None,
        parameters: Optional[Dict[str, Any]] = None,
        priority: int = 0,
        timeout: Optional[int] = None,
        sequence_id=0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        client_timeout: Optional[float] = None,
        prepared_request=None,
    ) -> pb.ModelInferResponse:
        if prepared_request is not None:
            # prepared requests are shared/reused: serialize a clone so
            # the correlation id never races concurrent senders
            clone = pb.ModelInferRequest()
            clone.CopyFrom(prepared_request)
            rid = clone.id or self.next_id()
            clone.id = rid
            clone.parameters["multiplex"].bool_param = True
            return await self._send(
                rid, clone.SerializeToString(), client_timeout
            )
        rid = request_id or self.next_id()
        data = None
        if not sequence_id and priority == 0 and timeout is None:
            params = {"multiplex": True}
            if parameters:
                params.update(parameters)
                params["multiplex"] = True
            data = self._builder.build(
                model_name, inputs, model_version, rid, outputs, params
            )
        if data is None:
            data = _proto_request_bytes(
                model_name,
                inputs,
                model_version,
                rid,
                outputs,
                parameters,
                priority,
                timeout,
                sequence_id,
                sequence_start,
                sequence_end,
            )
        return await self._send(rid, data, client_timeout)

    async def _send(
        self, rid: str, data: bytes, client_timeout: Optional[float]
    ) -> pb.ModelInferResponse:
        self._ensure_open()
        call = self._call
        future = asyncio.get_running_loop().create_future()
        self._pending[rid] = future
        try:
            async with self._write_lock:
                await call.write(data)
        except BaseException as e:
            self._pending.pop(rid, None)
            if isinstance(e, grpc.RpcError):
                raise rpc_error_to_exception(e) from None
            raise
        try:
            if client_timeout is not None:
                return await asyncio.wait_for(future, client_timeout)
            return await future
        except asyncio.TimeoutError:
            self._pending.pop(rid, None)
            raise InferenceServerException(
                f"timeout waiting for multiplexed response to '{rid}'"
            ) from None

    async def close(self) -> None:
        call, self._call = self._call, None
        reader, self._reader = self._reader, None
        if call is not None:
            call.cancel()
        if reader is not None:
            reader.cancel()
            try:
                await reader
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._fail_pending(
            InferenceServerException(
                "multiplexed stream closed",
                status="StatusCode.CANCELLED",
            )
        )


class _Slot:
    __slots__ = ("event", "response", "error")

    def __init__(self):
        self.event = threading.Event()
        self.response = None
        self.error: Optional[Exception] = None


class SyncStreamMultiplexer:
    """One long-lived bidi stream multiplexing unary infers (blocking).

    Built on :class:`InferStream`, so the PR-1 resilience applies: with
    a client retry policy, an UNAVAILABLE teardown reconnects with
    backoff, surfacing in-flight requests as retryable errors.
    """

    def __init__(self, client):
        self._client = client
        self._lock = threading.Lock()
        self._pending: Dict[str, _Slot] = {}
        self._counter = 0
        self._stream = None
        self.endpoint = None

    def _open_call(self, request_iterator, timeout=None):
        endpoint = self._client._pool.pick()
        self.endpoint = endpoint
        return self._client._stub_for(endpoint.url).ModelStreamInfer(
            request_iterator,
            metadata=self._client._metadata(None),
            timeout=timeout,
        )

    def _ensure_open(self) -> None:
        from client_tpu.grpc._infer_stream import InferStream

        with self._lock:
            if self._stream is not None and self._stream.is_active():
                return
            stream = InferStream(
                self._on_response,
                retry_policy=self._client._retry_policy,
            )
            stream.init_handler(
                self._open_call(stream.request_iterator),
                reconnect=self._open_call,
            )
            self._stream = stream

    def _on_response(self, result, error) -> None:
        if error is not None:
            if error.status() is None:
                # in-band frames carry only message text: restore the
                # retry-relevant status so resilience/failover still work
                derived = _derive_status(error.message())
                if derived is not None:
                    restored = InferenceServerException(
                        error.message(), status=derived
                    )
                    restored.request_id = getattr(error, "request_id", "")
                    error = restored
            rid = getattr(error, "request_id", "") or ""
            if rid:
                with self._lock:
                    slot = self._pending.pop(rid, None)
                slots = [slot] if slot is not None else []
            else:
                # stream-level failure with no id: every waiter fails
                with self._lock:
                    slots = list(self._pending.values())
                    self._pending.clear()
            for slot in slots:
                slot.error = error
                slot.event.set()
            return
        response = result.get_response()
        with self._lock:
            slot = self._pending.pop(response.id, None)
        if slot is not None:
            slot.response = response
            slot.event.set()

    def infer(self, request, client_timeout: Optional[float] = None):
        """Send one prepared ModelInferRequest over the stream and block
        for its correlated response. Mutates ``request.id`` (when empty)
        and stamps the ``multiplex`` parameter."""
        self._ensure_open()
        slot = _Slot()
        with self._lock:
            if not request.id:
                self._counter += 1
                request.id = f"mx{self._counter}"
            request.parameters["multiplex"].bool_param = True
            self._pending[request.id] = slot
        try:
            self._stream.enqueue_request(request)
        except BaseException:
            with self._lock:
                self._pending.pop(request.id, None)
            raise
        deadline = client_timeout if client_timeout is not None else 3600.0
        if not slot.event.wait(deadline):
            with self._lock:
                self._pending.pop(request.id, None)
            raise InferenceServerException(
                f"timeout waiting for multiplexed response to "
                f"'{request.id}'"
            )
        if slot.error is not None:
            raise slot.error
        return slot.response

    def close(self) -> None:
        with self._lock:
            stream, self._stream = self._stream, None
            slots = list(self._pending.values())
            self._pending.clear()
        if stream is not None:
            stream.close(cancel_requests=True)
        error = InferenceServerException(
            "multiplexed stream closed", status="StatusCode.CANCELLED"
        )
        for slot in slots:
            slot.error = error
            slot.event.set()
