"""Synchronous gRPC client for KServe v2 inference servers.

Capability parity with the reference gRPC client
(reference src/python/library/tritonclient/grpc/_client.py:119-1900):
health/metadata/config, repository control, statistics, trace/log settings,
system/CUDA/TPU shared-memory registration, unary + async + decoupled
streaming inference with cancellation, SSL and keepalive tuning, message
size capped at INT32_MAX both directions.
"""

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

import grpc

from client_tpu._client import InferenceServerClientBase
from client_tpu._request import Request
from client_tpu.grpc._generated import grpc_service_pb2 as service_pb2
from client_tpu.grpc._generated import model_config_pb2
from client_tpu.grpc._infer_input import InferInput
from client_tpu.grpc._infer_result import InferResult
from client_tpu.grpc._infer_stream import InferStream
from client_tpu.grpc._requested_output import InferRequestedOutput
from client_tpu.grpc._service_stubs import GRPCInferenceServiceStub
from client_tpu.grpc._utils import (
    get_inference_request,
    is_sequence_request as _is_sequence_request,
    request_is_hedgeable,
    request_routing_key,
    rpc_error_to_exception,
)
from client_tpu.lifecycle import (
    EndpointPool,
    failover_retry_policy,
    grpc_status_is_endpoint_outage,
    resolve_hedge_policy,
    status_is_unavailable,
)
from client_tpu.observability.trace import (
    NOOP_TRACE,
    TRACEPARENT_HEADER,
    Tracer,
    start_trace,
)
from client_tpu.resilience import (
    CircuitBreaker,
    CircuitBreakerOpenError,
    RetryPolicy,
    record_breaker_outcome,
    run_with_resilience,
    sequence_is_idempotent,
)
from client_tpu.utils import InferenceServerException

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "KeepAliveOptions",
    "CallContext",
    "service_pb2",
    "model_config_pb2",
]

# INT32_MAX: same cap as the reference (grpc/_client.py:53-54)
MAX_GRPC_MESSAGE_SIZE = 2**31 - 1


@dataclasses.dataclass
class KeepAliveOptions:
    """gRPC keepalive tuning (reference grpc/_client.py:57-99)."""

    keepalive_time_ms: int = 2**31 - 1
    keepalive_timeout_ms: int = 20000
    keepalive_permit_without_calls: bool = False
    http2_max_pings_without_data: int = 2


class CallContext:
    """Handle to an in-flight async_infer call (supports cancellation)."""

    def __init__(self, future):
        self._future = future

    def cancel(self) -> bool:
        """Cancel the request if still in flight."""
        return self._future.cancel()

    def get_result(self, timeout: Optional[float] = None) -> InferResult:
        """Block for and return the InferResult."""
        try:
            return InferResult(self._future.result(timeout=timeout))
        except grpc.RpcError as e:
            raise rpc_error_to_exception(e) from None
        except grpc.FutureTimeoutError:
            raise InferenceServerException(
                "timeout waiting for async infer result"
            ) from None
        except grpc.FutureCancelledError:
            raise InferenceServerException("request was cancelled") from None


def _to_json(message):
    from google.protobuf import json_format

    return json_format.MessageToDict(message, preserving_proto_field_name=True)


class InferenceServerClient(InferenceServerClientBase):
    """Synchronous client for the KServe v2 gRPC protocol."""

    def __init__(
        self,
        url=None,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional[grpc.ChannelCredentials] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[List] = None,
        retry_policy: Optional[RetryPolicy] = None,
        circuit_breaker: Optional[CircuitBreaker] = None,
        tracer: Optional[Tracer] = None,
        urls=None,
        endpoint_cooldown_s: float = 1.0,
        logger=None,
        stream_mode: bool = False,
        routing_policy=None,
        hedge_policy=None,
    ):
        """``url`` may be a single ``host:port``, a comma list, or an
        :class:`~client_tpu.lifecycle.EndpointPool`; ``urls=[...]`` names
        replica endpoints. One channel per endpoint (created lazily);
        unary RPCs route per ``routing_policy`` — sticky primary by
        default, or ``round_robin`` / ``least_outstanding`` / ``p2c`` /
        ``consistent_hash`` (affinity on the ``routing_key`` request
        parameter) — and fail over, immediately, no backoff sleep, when
        an endpoint answers UNAVAILABLE or the connection dies;
        recovering endpoints must pass a ``ServerReady`` probe first.
        Streams bind to the endpoint current at open. ``hedge_policy``
        (seconds, ``"p95"``, or a
        :class:`~client_tpu.lifecycle.HedgePolicy`) arms tail hedging on
        idempotent ModelInfer calls (gRPC futures under the hood): first
        response wins, the loser is cancelled and never double-counted
        in pool telemetry or retries; shm-ring/shared-memory requests
        never hedge.

        ``stream_mode=True`` routes every unary :meth:`infer` over one
        long-lived multiplexed ``ModelStreamInfer`` stream (correlation
        ids, concurrent server-side execution), amortizing per-RPC setup
        — the small-request fast path. With a ``retry_policy`` the
        stream reconnects on UNAVAILABLE (PR-1 stream machinery).
        Requests carrying explicit ``request_id`` must keep them unique
        while in flight."""
        super().__init__()
        self._verbose = verbose
        self._stream_mode = stream_mode
        self._mux = None
        self._mux_init_lock = threading.Lock()
        self._pool = EndpointPool.resolve(
            url,
            urls,
            cooldown_s=endpoint_cooldown_s,
            logger=logger,
            routing_policy=routing_policy,
        )
        self._hedge = resolve_hedge_policy(hedge_policy)
        if self._pool.size > 1 and retry_policy is None:
            retry_policy = failover_retry_policy(self._pool.size)
        self._retry_policy = retry_policy
        self._circuit_breaker = circuit_breaker
        self._tracer = tracer
        if channel_args is not None:
            options = list(channel_args)
        else:
            options = [
                ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
                ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
                ("grpc.primary_user_agent", "client-tpu-grpc"),
            ]
            if keepalive_options is not None:
                options += [
                    ("grpc.keepalive_time_ms", keepalive_options.keepalive_time_ms),
                    (
                        "grpc.keepalive_timeout_ms",
                        keepalive_options.keepalive_timeout_ms,
                    ),
                    (
                        "grpc.keepalive_permit_without_calls",
                        int(keepalive_options.keepalive_permit_without_calls),
                    ),
                    (
                        "grpc.http2.max_pings_without_data",
                        keepalive_options.http2_max_pings_without_data,
                    ),
                ]
        self._channel_options = options
        if creds is not None:
            self._credentials: Optional[grpc.ChannelCredentials] = creds
        elif ssl:

            def _read(path):
                if path is None:
                    return None
                with open(path, "rb") as f:
                    return f.read()

            self._credentials = grpc.ssl_channel_credentials(
                root_certificates=_read(root_certificates),
                private_key=_read(private_key),
                certificate_chain=_read(certificate_chain),
            )
        else:
            self._credentials = None
        self._channels: Dict[str, grpc.Channel] = {}
        self._stubs: Dict[str, GRPCInferenceServiceStub] = {}
        # primary-bound aliases (streams and subclasses use them)
        self._channel = self._channel_for(self._pool.urls[0])
        self._client_stub = self._stub_for(self._pool.urls[0])
        self._stream: Optional[InferStream] = None
        # the endpoint the decoupled stream is pinned to (stream traffic
        # is counted per stream, not per request)
        self._stream_endpoint = None

    def _channel_for(self, url: str) -> grpc.Channel:
        channel = self._channels.get(url)
        if channel is None:
            if self._credentials is not None:
                channel = grpc.secure_channel(
                    url, self._credentials, options=self._channel_options
                )
            else:
                channel = grpc.insecure_channel(
                    url, options=self._channel_options
                )
            self._channels[url] = channel
        return channel

    def _stub_for(self, url: str) -> GRPCInferenceServiceStub:
        stub = self._stubs.get(url)
        if stub is None:
            stub = GRPCInferenceServiceStub(self._channel_for(url))
            self._stubs[url] = stub
        return stub

    def _probe_endpoint(self, endpoint, timeout: float = 1.0) -> bool:
        """ServerReady against a specific endpoint (the gRPC face of the
        /v2/health/ready check the pool demands of recovering members)."""
        try:
            response = self._stub_for(endpoint.url).ServerReady(
                service_pb2.ServerReadyRequest(), timeout=timeout
            )
            return bool(response.ready)
        except grpc.RpcError:
            return False

    def _pick_endpoint(
        self,
        budget_s: Optional[float] = None,
        exclude=None,
        key=None,
    ):
        """Pool choice for the next attempt; recovering endpoints pass a
        ServerReady probe first, budgeted against the attempt timeout.
        ``exclude`` asks for an endpoint other than the one given (the
        hedge path); ``key`` is the consistent-hash routing key."""
        pool = self._pool
        probe_timeout = 1.0
        if budget_s:
            probe_timeout = min(1.0, max(0.05, budget_s / pool.size))
        for _ in range(pool.size):
            endpoint = pool.pick(key=key, exclude=exclude)
            if not pool.needs_probe(endpoint):
                return endpoint
            if self._probe_endpoint(endpoint, timeout=probe_timeout):
                pool.mark_up(endpoint)
                return endpoint
            pool.mark_down(endpoint)
        return pool.pick(key=key, exclude=exclude)

    # -- plumbing -----------------------------------------------------------

    def _metadata(self, headers: Optional[Dict[str, str]]):
        request = Request(headers or {})
        self._call_plugin(request)
        return tuple((k.lower(), v) for k, v in request.headers.items()) or None

    def _call(
        self,
        name,
        request,
        headers=None,
        client_timeout=None,
        compression_algorithm=None,
        idempotent=True,
        probe=False,
        trace=NOOP_TRACE,
        routing_key=None,
        hedgeable=True,
    ):
        """One RPC under the retry/deadline/breaker rules.

        ``client_timeout`` is the total budget across attempts; each
        attempt's gRPC timeout is derived from what remains of it.
        ``probe`` marks liveness/readiness checks: single attempt, no
        breaker accounting (a probe reports current state; its failures
        during a restart must not poison a shared breaker). An active
        ``trace`` records one "request" span per attempt (the blocking
        stub cannot split send from wait). ``routing_key`` feeds
        consistent-hash affinity; ``hedgeable`` (with the client's hedge
        policy armed and ``idempotent``) runs the attempt through the
        futures-based hedge orchestration.
        """
        if self._verbose:
            print(f"gRPC {name}: {{{str(request)[:200]}}}")
        metadata = self._metadata(headers)
        compression = _grpc_compression(compression_algorithm)
        if probe:
            try:
                return getattr(self._stub_for(self._pool.pick().url), name)(
                    request,
                    metadata=metadata,
                    timeout=client_timeout,
                    compression=compression,
                )
            except grpc.RpcError as e:
                raise rpc_error_to_exception(e) from None
        pool = self._pool

        def _classify_failure(endpoint, rpc_error):
            exc = rpc_error_to_exception(rpc_error)
            if grpc_status_is_endpoint_outage(exc.status()):
                # draining/dead endpoint — or a server that CANCELLED an
                # accepted RPC mid-shutdown (a local cancel raises
                # FutureCancelledError, never an RpcError): bench it;
                # with an alternative, skip the backoff and fail over NOW
                pool.observe(endpoint, token="StatusCode.UNAVAILABLE")
                if pool.has_alternative(endpoint):
                    exc.retry_backoff_cap_s = 0.0
            return exc

        hedge = self._hedge if (hedgeable and idempotent) else None
        if hedge is not None:

            def _send(attempt_timeout):
                return self._hedged_send(
                    name,
                    request,
                    metadata,
                    compression,
                    attempt_timeout,
                    routing_key,
                    _classify_failure,
                )

        else:

            def _send(attempt_timeout):
                endpoint = self._pick_endpoint(
                    attempt_timeout, key=routing_key
                )
                started = pool.begin(endpoint)
                try:
                    value = getattr(self._stub_for(endpoint.url), name)(
                        request,
                        metadata=metadata,
                        timeout=attempt_timeout,
                        compression=compression,
                    )
                except grpc.RpcError as e:
                    exc = _classify_failure(endpoint, e)
                    # the token keeps client-fault codes out of
                    # consecutive-error ejection
                    pool.finish(
                        endpoint, started, ok=False, token=exc.status()
                    )
                    raise exc from None
                except BaseException:
                    # an unwrapped error: close the bracket so the
                    # outstanding gauge never leaks
                    pool.finish(endpoint, started, ok=False)
                    raise
                pool.finish(endpoint, started, ok=True)
                pool.observe(endpoint, ok=True)
                return value

        return run_with_resilience(
            trace.wrap_attempt(_send),
            retry_policy=self._retry_policy,
            circuit_breaker=self._circuit_breaker,
            budget_s=client_timeout,
            idempotent=idempotent,
            description=f"gRPC {name}",
        )

    def _hedged_send(
        self,
        name,
        request,
        metadata,
        compression,
        attempt_timeout,
        routing_key,
        classify_failure,
    ):
        """One hedged attempt over gRPC futures (the blocking twin of
        :func:`client_tpu.lifecycle.hedged_send_async`): launch the
        primary, and past the hedge delay one duplicate on a different
        endpoint; first success wins, the loser is cancelled with its
        pool bracket closed as ``cancelled`` (neither an error nor a
        latency sample — never double-counted). Exactly one outcome (the
        winner's, or the primary's when both fail) reaches the retry
        loop. Any unexpected failure mid-orchestration (a channel closed
        under us, a pick raising) cancels every launched future and
        closes its bracket before propagating — the outstanding gauge
        must never leak."""
        pool = self._pool
        hedge = self._hedge
        settled = threading.Event()
        entries = []

        def _launch(endpoint, timeout=attempt_timeout):
            started = pool.begin(endpoint)
            try:
                future = getattr(
                    self._stub_for(endpoint.url), name
                ).future(
                    request,
                    metadata=metadata,
                    timeout=timeout,
                    compression=compression,
                )
            except BaseException:
                pool.finish(endpoint, started, ok=False)
                raise
            future.add_done_callback(lambda _f: settled.set())
            entry = {
                "future": future,
                "endpoint": endpoint,
                "started": started,
                "closed": False,
            }
            entries.append(entry)
            return entry

        def _close(entry, ok=False, cancelled=False, token=None):
            if entry["closed"]:
                return 0.0
            entry["closed"] = True
            return pool.finish(
                entry["endpoint"], entry["started"],
                ok=ok, cancelled=cancelled, token=token,
            )

        def _outcome(future):
            """("ok", response) | ("err", rpc_error) | ("cancelled", None)."""
            try:
                exc = future.exception()
            except (grpc.FutureCancelledError, grpc.FutureTimeoutError):
                return ("cancelled", None)
            if exc is not None:
                return ("err", exc)
            return ("ok", future.result())

        try:
            primary = _launch(
                self._pick_endpoint(attempt_timeout, key=routing_key)
            )
            delay = hedge.current_delay_s()
            if delay is not None:
                if attempt_timeout:
                    delay = min(delay, attempt_timeout)
                if not settled.wait(delay):
                    # the hedge rides what REMAINS of the attempt budget
                    # (~delay has elapsed); its own full attempt_timeout
                    # would overrun the caller's deadline by the delay
                    hedge_timeout = (
                        max(0.001, attempt_timeout - delay)
                        if attempt_timeout
                        else None
                    )
                    other = self._pick_endpoint(
                        hedge_timeout,
                        exclude=primary["endpoint"],
                        key=routing_key,
                    )
                    if other is not None and other is not primary["endpoint"]:
                        pool.note_hedge()
                        _launch(other, hedge_timeout)
            winner = None
            while winner is None:
                settled.clear()
                done = [e for e in entries if e["future"].done()]
                for entry in done:
                    if _outcome(entry["future"])[0] == "ok":
                        winner = entry
                        break
                if winner is not None or len(done) == len(entries):
                    break
                settled.wait(attempt_timeout if attempt_timeout else 3600.0)
            for entry in entries:
                if entry is winner:
                    continue
                entry["future"].cancel()
                kind, payload = _outcome(entry["future"])
                if winner is None and entry is primary:
                    continue  # the primary's failure is settled below
                if kind == "err":
                    # the loser genuinely failed before cancellation: a
                    # real endpoint error, booked as one (but its outcome
                    # never reaches the retry loop)
                    exc = classify_failure(entry["endpoint"], payload)
                    _close(entry, ok=False, token=exc.status())
                else:
                    # cancelled (or succeeded after losing): says nothing
                    # we need — close the bracket without booking anything
                    _close(entry, cancelled=True)
            if winner is not None:
                latency_s = _close(winner, ok=True)
                hedge.record(latency_s)
                pool.observe(winner["endpoint"], ok=True)
                if winner is not primary:
                    pool.note_hedge_win()
                return winner["future"].result()
            # both attempts failed: the primary's outcome speaks for it
            kind, payload = _outcome(primary["future"])
            if kind == "err":
                exc = classify_failure(primary["endpoint"], payload)
                _close(primary, ok=False, token=exc.status())
                raise exc from None
            _close(primary, ok=False)
            raise InferenceServerException(
                f"gRPC {name} was cancelled", status="CANCELLED"
            )
        finally:
            # unexpected escape (channel closed mid-orchestration, pick
            # raising): no launched attempt may keep running with an open
            # bracket
            for entry in entries:
                if not entry["closed"]:
                    entry["future"].cancel()
                    _close(entry, cancelled=True)

    def _mux_infer(self, request, client_timeout, trace, idempotent=True):
        """One multiplexed-stream infer under the retry/breaker rules,
        with per-request endpoint-pool telemetry."""
        if self._mux is None:
            from client_tpu.grpc._mux import SyncStreamMultiplexer

            # double-checked under a lock: two threads' first infers
            # must not each open (and one leak) a stream
            with self._mux_init_lock:
                if self._mux is None:
                    self._mux = SyncStreamMultiplexer(self)
        mux = self._mux
        pool = self._pool

        def _send(attempt_timeout):
            mux._ensure_open()
            endpoint = mux.endpoint
            started = pool.begin(endpoint)
            try:
                value = mux.infer(request, client_timeout=attempt_timeout)
            except InferenceServerException as e:
                pool.finish(endpoint, started, ok=False)
                if status_is_unavailable(e.status()):
                    pool.observe(endpoint, token=e.status())
                    if pool.has_alternative(endpoint):
                        e.retry_backoff_cap_s = 0.0
                raise
            except BaseException:
                pool.finish(endpoint, started, ok=False)
                raise
            pool.finish(endpoint, started, ok=True)
            pool.observe(endpoint, ok=True)
            return value

        return run_with_resilience(
            trace.wrap_attempt(_send),
            retry_policy=self._retry_policy,
            circuit_breaker=self._circuit_breaker,
            budget_s=client_timeout,
            idempotent=idempotent,
            description="gRPC mux ModelInfer",
        )

    def close(self) -> None:
        """Close every endpoint channel (stops any active stream first)."""
        self.stop_stream()
        if self._mux is not None:
            mux, self._mux = self._mux, None
            mux.close()
        for channel in self._channels.values():
            channel.close()

    def endpoint_snapshot(self) -> dict:
        """Live per-endpoint pool telemetry — outstanding requests, EWMA
        latency, error/reroute counters per endpoint (see
        :meth:`~client_tpu.lifecycle.EndpointPool.snapshot`). Unary
        calls are begin/finish-bracketed; the bidirectional stream pins
        its endpoint at open and is not counted per-request."""
        return self._pool.snapshot()

    def __enter__(self) -> "InferenceServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- health -------------------------------------------------------------

    def is_server_live(self, headers=None, client_timeout=None) -> bool:
        response = self._call(
            "ServerLive",
            service_pb2.ServerLiveRequest(),
            headers,
            client_timeout,
            probe=True,
        )
        return response.live

    def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        response = self._call(
            "ServerReady",
            service_pb2.ServerReadyRequest(),
            headers,
            client_timeout,
            probe=True,
        )
        return response.ready

    def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ) -> bool:
        response = self._call(
            "ModelReady",
            service_pb2.ModelReadyRequest(name=model_name, version=model_version),
            headers,
            client_timeout,
            probe=True,
        )
        return response.ready

    # -- metadata / config ---------------------------------------------------

    def get_server_metadata(self, headers=None, as_json=False, client_timeout=None):
        response = self._call(
            "ServerMetadata",
            service_pb2.ServerMetadataRequest(),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    def get_model_metadata(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        response = self._call(
            "ModelMetadata",
            service_pb2.ModelMetadataRequest(
                name=model_name, version=model_version
            ),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    def get_model_config(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        response = self._call(
            "ModelConfig",
            service_pb2.ModelConfigRequest(
                name=model_name, version=model_version
            ),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    # -- repository ----------------------------------------------------------

    def get_model_repository_index(
        self, headers=None, as_json=False, client_timeout=None
    ):
        response = self._call(
            "RepositoryIndex",
            service_pb2.RepositoryIndexRequest(),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    def load_model(
        self,
        model_name,
        headers=None,
        config: Optional[str] = None,
        files: Optional[Dict[str, bytes]] = None,
        client_timeout=None,
    ) -> None:
        request = service_pb2.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        if files:
            for name, content in files.items():
                request.parameters[name].bytes_param = content
        self._call(
            "RepositoryModelLoad",
            request,
            headers,
            client_timeout,
            idempotent=False,
        )

    def unload_model(
        self,
        model_name,
        headers=None,
        unload_dependents: bool = False,
        client_timeout=None,
    ) -> None:
        request = service_pb2.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        self._call(
            "RepositoryModelUnload",
            request,
            headers,
            client_timeout,
            idempotent=False,
        )

    # -- statistics / settings -----------------------------------------------

    def get_inference_statistics(
        self,
        model_name="",
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        response = self._call(
            "ModelStatistics",
            service_pb2.ModelStatisticsRequest(
                name=model_name, version=model_version
            ),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    def update_trace_settings(
        self,
        model_name=None,
        settings: Optional[Dict[str, Any]] = None,
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        request = service_pb2.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is None:
                # empty entry = clear/reset this setting (Triton semantics)
                request.settings[key].SetInParent()
                continue
            values = value if isinstance(value, (list, tuple)) else [value]
            request.settings[key].value.extend(str(v) for v in values)
        response = self._call("TraceSetting", request, headers, client_timeout)
        return _to_json(response) if as_json else response

    def get_trace_settings(
        self, model_name=None, headers=None, as_json=False, client_timeout=None
    ):
        request = service_pb2.TraceSettingRequest(model_name=model_name or "")
        response = self._call("TraceSetting", request, headers, client_timeout)
        return _to_json(response) if as_json else response

    def update_log_settings(
        self, settings: Dict[str, Any], headers=None, as_json=False, client_timeout=None
    ):
        request = service_pb2.LogSettingsRequest()
        for key, value in settings.items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            else:
                request.settings[key].string_param = str(value)
        response = self._call("LogSettings", request, headers, client_timeout)
        return _to_json(response) if as_json else response

    def get_log_settings(self, headers=None, as_json=False, client_timeout=None):
        response = self._call(
            "LogSettings", service_pb2.LogSettingsRequest(), headers, client_timeout
        )
        return _to_json(response) if as_json else response

    # -- shared memory -------------------------------------------------------

    def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        response = self._call(
            "SystemSharedMemoryStatus",
            service_pb2.SystemSharedMemoryStatusRequest(name=region_name),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ) -> None:
        self._call(
            "SystemSharedMemoryRegister",
            service_pb2.SystemSharedMemoryRegisterRequest(
                name=name, key=key, offset=offset, byte_size=byte_size
            ),
            headers,
            client_timeout,
            idempotent=False,
        )

    def unregister_system_shared_memory(
        self, name="", headers=None, client_timeout=None
    ) -> None:
        self._call(
            "SystemSharedMemoryUnregister",
            service_pb2.SystemSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
            idempotent=False,
        )

    def get_cuda_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        response = self._call(
            "CudaSharedMemoryStatus",
            service_pb2.CudaSharedMemoryStatusRequest(name=region_name),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ) -> None:
        self._call(
            "CudaSharedMemoryRegister",
            service_pb2.CudaSharedMemoryRegisterRequest(
                name=name,
                raw_handle=raw_handle,
                device_id=device_id,
                byte_size=byte_size,
            ),
            headers,
            client_timeout,
            idempotent=False,
        )

    def unregister_cuda_shared_memory(
        self, name="", headers=None, client_timeout=None
    ) -> None:
        self._call(
            "CudaSharedMemoryUnregister",
            service_pb2.CudaSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
            idempotent=False,
        )

    def get_tpu_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        response = self._call(
            "TpuSharedMemoryStatus",
            service_pb2.TpuSharedMemoryStatusRequest(name=region_name),
            headers,
            client_timeout,
        )
        return _to_json(response) if as_json else response

    def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ) -> None:
        """Register a TPU shared-memory region (client_tpu extension)."""
        self._call(
            "TpuSharedMemoryRegister",
            service_pb2.TpuSharedMemoryRegisterRequest(
                name=name,
                raw_handle=raw_handle,
                device_id=device_id,
                byte_size=byte_size,
            ),
            headers,
            client_timeout,
            idempotent=False,
        )

    def unregister_tpu_shared_memory(
        self, name="", headers=None, client_timeout=None
    ) -> None:
        self._call(
            "TpuSharedMemoryUnregister",
            service_pb2.TpuSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
            idempotent=False,
        )

    # -- inference -----------------------------------------------------------

    def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: Union[int, str] = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
        parameters: Optional[Dict[str, Any]] = None,
    ) -> InferResult:
        """Run an inference and block for the result."""
        trace = start_trace(
            self._tracer, "infer", surface="grpc", model=model_name
        )
        try:
            with trace.stage("serialize"):
                request = get_inference_request(
                    model_name,
                    inputs,
                    model_version=model_version,
                    request_id=request_id,
                    outputs=outputs,
                    sequence_id=sequence_id,
                    sequence_start=sequence_start,
                    sequence_end=sequence_end,
                    priority=priority,
                    timeout=timeout,
                    parameters=parameters,
                )
            if (
                self._stream_mode
                and headers is None
                and compression_algorithm is None
                # a sampled traceparent must ride per-request metadata,
                # which the long-lived stream cannot carry: traced
                # requests take the unary path so W3C propagation works
                and not trace.traceparent
            ):
                # persistent multiplexed stream: amortizes per-RPC setup;
                # per-request headers/compression need the unary path
                response = self._mux_infer(
                    request,
                    client_timeout,
                    trace,
                    idempotent=sequence_is_idempotent(sequence_id),
                )
                with trace.stage("deserialize"):
                    result = InferResult(response)
                trace.finish()
                return result
            if trace.traceparent:
                headers = {
                    **(headers or {}),
                    TRACEPARENT_HEADER: trace.traceparent,
                }
            response = self._call(
                "ModelInfer",
                request,
                headers,
                client_timeout,
                compression_algorithm=compression_algorithm,
                idempotent=sequence_is_idempotent(sequence_id),
                trace=trace,
                routing_key=self._request_routing_key(request),
                hedgeable=self._request_hedgeable(request),
            )
            with trace.stage("deserialize"):
                result = InferResult(response)
        except BaseException as e:
            trace.finish(error=e)
            raise
        trace.finish()
        return result

    def _request_routing_key(self, request):
        """The consistent-hash key of a built request, read from the
        policy's key parameter (zero work unless such a policy is on)."""
        return request_routing_key(request, self._pool.key_parameter)

    def _request_hedgeable(self, request) -> bool:
        """Requests referencing single-writer buffers (shm-ring tickets,
        shared-memory regions) never hedge — shared classification in
        :func:`client_tpu.grpc._utils.request_is_hedgeable` (checked
        only while hedging is armed)."""
        return self._hedge is None or request_is_hedgeable(request)

    @staticmethod
    def prepare_request(
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: Union[int, str] = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        parameters: Optional[Dict[str, Any]] = None,
    ):
        """Build a reusable ``ModelInferRequest`` for :meth:`infer_prepared`
        (reference PreRunProcessing proto reuse, grpc_client.cc:1419-1580)."""
        return get_inference_request(
            model_name,
            inputs,
            model_version=model_version,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )

    def infer_prepared(
        self,
        request,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
    ) -> InferResult:
        """Send a request built by :meth:`prepare_request` (reusable)."""
        trace = start_trace(
            self._tracer, "infer", surface="grpc", model=request.model_name
        )
        if (
            self._stream_mode
            and headers is None
            and compression_algorithm is None
            and not trace.traceparent
        ):
            # prepared requests are shared/reused: the mux mutates the
            # correlation id, so send a clone
            clone = service_pb2.ModelInferRequest()
            clone.CopyFrom(request)
            try:
                response = self._mux_infer(
                    clone,
                    client_timeout,
                    trace,
                    idempotent=not _is_sequence_request(request),
                )
                with trace.stage("deserialize"):
                    result = InferResult(response)
            except BaseException as e:
                trace.finish(error=e)
                raise
            trace.finish()
            return result
        if trace.traceparent:
            headers = {
                **(headers or {}),
                TRACEPARENT_HEADER: trace.traceparent,
            }
        try:
            response = self._call(
                "ModelInfer",
                request,
                headers,
                client_timeout,
                compression_algorithm=compression_algorithm,
                idempotent=not _is_sequence_request(request),
                trace=trace,
                routing_key=self._request_routing_key(request),
                hedgeable=self._request_hedgeable(request),
            )
            with trace.stage("deserialize"):
                result = InferResult(response)
        except BaseException as e:
            trace.finish(error=e)
            raise
        trace.finish()
        return result

    def async_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        callback,
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: Union[int, str] = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
        parameters: Optional[Dict[str, Any]] = None,
    ) -> CallContext:
        """Issue an inference without blocking.

        ``callback(result, error)`` fires from a gRPC thread on completion.
        Returns a :class:`CallContext` whose ``cancel()`` aborts the call.

        The callback contract rules out transparent retries (the caller
        would see duplicate callbacks), but a configured circuit breaker
        is honored: an open breaker fails fast here, and outcomes feed
        its failure/success accounting.
        """
        if (
            self._circuit_breaker is not None
            and not self._circuit_breaker.allow()
        ):
            raise CircuitBreakerOpenError(
                "circuit breaker is open; gRPC async ModelInfer failed fast"
            )
        try:
            request = get_inference_request(
                model_name,
                inputs,
                model_version=model_version,
                request_id=request_id,
                outputs=outputs,
                sequence_id=sequence_id,
                sequence_start=sequence_start,
                sequence_end=sequence_end,
                priority=priority,
                timeout=timeout,
                parameters=parameters,
            )
            if self._verbose:
                print(f"gRPC async ModelInfer: {{{str(request)[:200]}}}")
            future = self._stub_for(
                self._pick_endpoint().url
            ).ModelInfer.future(
                request,
                metadata=self._metadata(headers),
                timeout=client_timeout,
                compression=_grpc_compression(compression_algorithm),
            )
        except BaseException as e:
            # a local failure between allow() and the RPC existing says
            # nothing about the server — release the (possible) half-open
            # probe slot instead of wedging the breaker
            record_breaker_outcome(self._circuit_breaker, e)
            raise

        def _done(f):
            # Build (result, error) first, then invoke the callback exactly
            # once — a raising user callback must not trigger a second,
            # contradictory invocation.
            result, error = None, None
            try:
                result = InferResult(f.result())
            except grpc.RpcError as e:
                error = rpc_error_to_exception(e)
            except grpc.FutureCancelledError:
                error = InferenceServerException("request was cancelled")
            except Exception as e:  # noqa: BLE001
                error = InferenceServerException(str(e))
            if self._circuit_breaker is not None:
                if error is None:
                    self._circuit_breaker.record_success()
                else:
                    record_breaker_outcome(self._circuit_breaker, error)
            callback(result, error)

        future.add_done_callback(_done)
        return CallContext(future)

    # -- decoupled streaming -------------------------------------------------

    def start_stream(
        self,
        callback,
        stream_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
    ) -> None:
        """Open the bidirectional inference stream.

        Only one stream per client at a time (the reference contract,
        reference grpc_client.cc:1327-1332). ``callback(result, error)``
        fires once per *response* — decoupled models may produce many
        responses per request.

        When the client has a ``retry_policy``, a stream torn down with
        ``UNAVAILABLE`` reconnects automatically (with the policy's
        backoff). Requests that were in flight on the dead connection
        are surfaced to the callback as errors — never silently
        replayed; requests still queued client-side carry over unsent.
        """
        if self._stream is not None and self._stream.is_active():
            raise InferenceServerException(
                "stream is already active; call stop_stream() first"
            )
        metadata = self._metadata(headers)
        compression = _grpc_compression(compression_algorithm)

        def _open(request_iterator, timeout=stream_timeout):
            # bound to the pool's CURRENT endpoint at each (re)open, so a
            # reconnect after UNAVAILABLE also fails over to a healthy
            # replica instead of re-dialing the dead one. The pin moves
            # with it: stream traffic is counted per STREAM (decoupled
            # requests have no per-request bracket) and excluded from the
            # routing policies' load signals.
            endpoint = self._pool.pick()
            if self._stream_endpoint is not None:
                self._pool.unpin_stream(self._stream_endpoint)
            self._stream_endpoint = endpoint
            self._pool.pin_stream(endpoint)
            return self._stub_for(endpoint.url).ModelStreamInfer(
                request_iterator,
                metadata=metadata,
                timeout=timeout,
                compression=compression,
            )

        self._stream = InferStream(
            callback,
            verbose=self._verbose,
            retry_policy=self._retry_policy,
            # stream_timeout is a total budget: reconnected calls get
            # only what remains of it
            stream_budget_s=stream_timeout,
        )
        self._stream.init_handler(
            _open(self._stream.request_iterator), reconnect=_open
        )

    def async_stream_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: Union[int, str] = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        enable_empty_final_response: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        parameters: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Send one request on the active stream (non-blocking)."""
        if self._stream is None or not self._stream.is_active():
            raise InferenceServerException(
                "stream is not active; call start_stream() first"
            )
        request = get_inference_request(
            model_name,
            inputs,
            model_version=model_version,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        if enable_empty_final_response:
            request.parameters[
                "triton_enable_empty_final_response"
            ].bool_param = True
        self._stream.enqueue_request(request)

    def stop_stream(self, cancel_requests: bool = False) -> None:
        """Close the active stream (if any)."""
        if self._stream is not None:
            self._stream.close(cancel_requests=cancel_requests)
            self._stream = None
        if self._stream_endpoint is not None:
            self._pool.unpin_stream(self._stream_endpoint)
            self._stream_endpoint = None


def _grpc_compression(algorithm: Optional[str]):
    if algorithm is None:
        return None
    mapping = {
        "deflate": grpc.Compression.Deflate,
        "gzip": grpc.Compression.Gzip,
        "none": grpc.Compression.NoCompression,
    }
    if algorithm not in mapping:
        raise InferenceServerException(
            f"unsupported compression algorithm '{algorithm}' "
            "(expected 'deflate', 'gzip', or 'none')"
        )
    return mapping[algorithm]
