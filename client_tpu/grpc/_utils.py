"""gRPC client helpers: request building and error mapping.

Reference semantics: src/python/library/tritonclient/grpc/_utils.py:80-158.
"""

from typing import Any, Dict, Optional

import grpc

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.utils import InferenceServerException


def raise_error(msg: str) -> None:
    raise InferenceServerException(msg)


def rpc_error_to_exception(rpc_error: grpc.RpcError) -> InferenceServerException:
    """Map a grpc.RpcError to the client exception type.

    A ``retry-after`` entry in the trailing metadata (seconds — what a
    shedding router or draining server attaches, the gRPC face of the
    HTTP ``Retry-After`` header) rides along as ``retry_after_s`` so the
    retry loop's server-hint backoff floor engages."""
    retry_after_s = None
    try:
        code = rpc_error.code()
        status = str(code) if code is not None else None
        details = rpc_error.details()
        trailing = rpc_error.trailing_metadata()
        if trailing:
            for key, value in trailing:
                if key == "retry-after":
                    try:
                        retry_after_s = max(0.0, float(value))
                    except (TypeError, ValueError):
                        pass
                    break
    except Exception:
        status = None
        details = str(rpc_error)
    error = InferenceServerException(
        details or "gRPC request failed", status=status
    )
    if retry_after_s is not None:
        error.retry_after_s = retry_after_s
    return error


def request_routing_key(request, key_parameter: Optional[str]):
    """The consistent-hash routing key of a built ModelInferRequest,
    read from the policy's key parameter (both gRPC clients; zero work
    when no keyed policy is installed — pass key_parameter=None)."""
    if key_parameter is None:
        return None
    if key_parameter in request.parameters:
        value = request.parameters[key_parameter]
        return value.string_param or value.int64_param
    return None


def request_is_hedgeable(request) -> bool:
    """False when a ModelInferRequest references a single-writer buffer
    — an shm-ring ticket or a shared-memory region on any input/output:
    two servers racing to fill one client-owned buffer would corrupt
    whichever response loses, so such requests never hedge. One helper
    so both gRPC clients classify identically (call only while hedging
    is armed)."""
    if "shm_ring_region" in request.parameters:
        return False
    for output in request.outputs:
        if "shared_memory_region" in output.parameters:
            return False
    for tensor in request.inputs:
        if "shared_memory_region" in tensor.parameters:
            return False
    return True


def is_sequence_request(request) -> bool:
    """True when a prepared ModelInferRequest carries sequence state
    (such requests are non-idempotent and must never be auto-retried)."""
    if "sequence_id" not in request.parameters:
        return False
    param = request.parameters["sequence_id"]
    return bool(param.int64_param or param.string_param)


def set_parameter(proto_params, key: str, value: Any) -> None:
    if isinstance(value, bool):
        proto_params[key].bool_param = value
    elif isinstance(value, int):
        proto_params[key].int64_param = value
    elif isinstance(value, float):
        proto_params[key].double_param = value
    elif isinstance(value, str):
        proto_params[key].string_param = value
    else:
        raise InferenceServerException(
            f"unsupported parameter type {type(value).__name__} for '{key}'"
        )


_RESERVED_PARAMS = frozenset(
    (
        "sequence_id",
        "sequence_start",
        "sequence_end",
        "priority",
        "timeout",
        "shared_memory_region",
        "shared_memory_byte_size",
        "shared_memory_offset",
        "classification",
        "binary_data",
        "binary_data_size",
        "binary_data_output",
    )
)


def get_inference_request(
    model_name: str,
    inputs,
    model_version: str = "",
    request_id: str = "",
    outputs=None,
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[Dict[str, Any]] = None,
) -> pb.ModelInferRequest:
    """Build a ModelInferRequest proto from client-side tensor objects."""
    request = pb.ModelInferRequest(
        model_name=model_name, model_version=model_version
    )
    if request_id:
        request.id = request_id
    if sequence_id != 0 and sequence_id != "":
        if isinstance(sequence_id, str):
            request.parameters["sequence_id"].string_param = sequence_id
        else:
            request.parameters["sequence_id"].int64_param = sequence_id
        request.parameters["sequence_start"].bool_param = bool(sequence_start)
        request.parameters["sequence_end"].bool_param = bool(sequence_end)
    if priority != 0:
        request.parameters["priority"].uint64_param = priority
    if timeout is not None:
        request.parameters["timeout"].int64_param = timeout
    if parameters:
        for key, value in parameters.items():
            if key in _RESERVED_PARAMS:
                raise InferenceServerException(
                    f"parameter '{key}' is reserved; use the dedicated "
                    "keyword argument"
                )
            set_parameter(request.parameters, key, value)
    for infer_input in inputs:
        tensor = request.inputs.add()
        tensor.CopyFrom(infer_input._get_tensor())
        raw = infer_input._get_raw_content()
        if raw is not None:
            request.raw_input_contents.append(raw)
    if outputs:
        for infer_output in outputs:
            request.outputs.add().CopyFrom(infer_output._get_tensor())
    return request
