"""Hand-rolled protobuf wire codec for the ModelInfer hot path.

The per-request cost of the gRPC inference path is dominated not by
parsing bytes (the C-backed protobuf runtime parses in ~1 us) but by
protobuf *object churn*: building a ``ModelInferRequest``/
``ModelInferResponse`` and crossing the Python/C boundary once per field
access — proto -> CoreRequest measures ~29 us/req on this host while
``FromString`` alone is ~1 us (PERF.md PR-11). This module removes the
object layer for the common small-request shape (raw tensor contents,
no per-tensor parameters, no typed ``contents``):

* :class:`RequestScanner` splits serialized ``ModelInferRequest`` bytes
  into a metadata *prefix* and the ``raw_input_contents`` tail with one
  cheap top-level tag walk, then memoizes the parsed prefix by its exact
  bytes — under load every request of a workload shares the prefix
  (same model/tensors/shapes; only the payload bytes differ), so the
  steady state is one dict hit plus zero-copy raw views.
* :func:`encode_infer_response` / :func:`encode_infer_request` build
  serialized messages into a caller-owned ``bytearray`` scratch,
  byte-identical to ``SerializeToString(deterministic=True)`` for the
  shapes they accept (fields in number order, packed shapes, map entries
  sorted by key) — guarded by the parity corpus in
  ``tests/test_shm_ring.py``.

Anything outside the fast shape returns ``None`` and the caller falls
back to the real protobuf codec — the fast path is an *optimization*,
never a fork of the protocol.

Wire schema (client_tpu/protos/grpc_service.proto):

    ModelInferRequest:  1 model_name, 2 model_version, 3 id,
                        4 parameters map, 5 inputs, 6 outputs,
                        7 raw_input_contents
    InferInputTensor:   1 name, 2 datatype, 3 shape (packed int64),
                        4 parameters map, 5 contents
    InferRequestedOutputTensor: 1 name, 2 parameters map
    ModelInferResponse: 1 model_name, 2 model_version, 3 id,
                        4 parameters map, 5 outputs,
                        6 raw_output_contents
    InferOutputTensor:  1 name, 2 datatype, 3 shape (packed int64),
                        4 parameters map, 5 contents
    InferParameter oneof: 1 bool, 2 int64, 3 string, 4 double, 5 uint64
    ModelStreamInferResponse: 1 error_message, 2 infer_response
"""

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

_U64_MASK = (1 << 64) - 1
_PACK_DOUBLE = struct.Struct("<d")

# top-level ModelInferRequest tags (all length-delimited, single-byte)
_TAG_MODEL_NAME = 0x0A
_TAG_MODEL_VERSION = 0x12
_TAG_ID = 0x1A
_TAG_PARAMS = 0x22
_TAG_INPUTS = 0x2A
_TAG_OUTPUTS = 0x32
_TAG_RAW = 0x3A
_KNOWN_TAGS = frozenset(
    (0x0A, 0x12, 0x1A, 0x22, 0x2A, 0x32, 0x3A)
)


class WireError(ValueError):
    """Structurally invalid bytes (not merely an unsupported shape)."""


# -- varint primitives --------------------------------------------------------


def read_varint(buf, pos: int) -> Tuple[int, int]:
    """Decode one base-128 varint at ``pos``; returns (value, new pos)."""
    result = 0
    shift = 0
    while True:
        try:
            b = buf[pos]
        except IndexError:
            raise WireError("truncated varint") from None
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise WireError("varint too long")


def write_varint(out: bytearray, value: int) -> None:
    """Append one base-128 varint (value must be in [0, 2**64))."""
    while value > 0x7F:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)


def _signed64(value: int) -> int:
    """Unsigned varint value -> int64 (two's complement)."""
    return value - (1 << 64) if value >= (1 << 63) else value


# -- InferParameter -----------------------------------------------------------


def _decode_parameter(buf: bytes, pos: int, end: int) -> Any:
    """Decode an InferParameter submessage body; oneof = last field wins
    (protobuf merge semantics)."""
    value: Any = None
    while pos < end:
        tag, pos = read_varint(buf, pos)
        if tag == 0x08:  # bool_param
            raw, pos = read_varint(buf, pos)
            value = bool(raw)
        elif tag == 0x10:  # int64_param
            raw, pos = read_varint(buf, pos)
            value = _signed64(raw)
        elif tag == 0x1A:  # string_param
            n, pos = read_varint(buf, pos)
            value = buf[pos : pos + n].decode("utf-8")
            pos += n
        elif tag == 0x21:  # double_param (fixed64)
            value = _PACK_DOUBLE.unpack_from(buf, pos)[0]
            pos += 8
        elif tag == 0x28:  # uint64_param
            value, pos = read_varint(buf, pos)
        else:
            raise WireError(f"unknown InferParameter tag {tag:#x}")
    return value


def _encode_parameter(out: bytearray, value: Any) -> None:
    """InferParameter body for one python value — same type mapping as
    the proto codec's ``dict_to_params``/``set_parameter`` (bool before
    int: bool is an int subclass)."""
    if isinstance(value, bool):
        out.append(0x08)
        out.append(1 if value else 0)
    elif isinstance(value, int):
        out.append(0x10)
        write_varint(out, value & _U64_MASK)
    elif isinstance(value, float):
        out.append(0x21)
        out += _PACK_DOUBLE.pack(value)
    else:
        data = str(value).encode("utf-8")
        out.append(0x1A)
        write_varint(out, len(data))
        out += data


def _encode_params_map(
    out: bytearray, field_tag: int, params: Dict[str, Any]
) -> None:
    """Map<string, InferParameter> entries, sorted by key (matching
    ``SerializeToString(deterministic=True)``)."""
    for key in sorted(params):
        entry = bytearray()
        key_bytes = key.encode("utf-8")
        if key_bytes:
            entry.append(0x0A)
            write_varint(entry, len(key_bytes))
            entry += key_bytes
        value = bytearray()
        _encode_parameter(value, params[key])
        entry.append(0x12)
        write_varint(entry, len(value))
        entry += value
        out.append(field_tag)
        write_varint(out, len(entry))
        out += entry


def _decode_map_entry(buf: bytes, pos: int, end: int) -> Tuple[str, Any]:
    key = ""
    value: Any = None
    while pos < end:
        tag, pos = read_varint(buf, pos)
        if tag == 0x0A:  # key
            n, pos = read_varint(buf, pos)
            key = buf[pos : pos + n].decode("utf-8")
            pos += n
        elif tag == 0x12:  # value (InferParameter)
            n, pos = read_varint(buf, pos)
            value = _decode_parameter(buf, pos, pos + n)
            pos += n
        else:
            raise WireError(f"unknown map-entry tag {tag:#x}")
    return key, value


# -- request decode -----------------------------------------------------------


class DecodedInfer:
    """Flat view of a fast-shape ModelInferRequest (no proto objects).

    Instances coming out of :class:`RequestScanner` are cached templates
    shared across requests — treat every field as READ-ONLY (copy
    ``parameters`` before mutating).
    """

    __slots__ = (
        "model_name",
        "model_version",
        "id",
        "parameters",
        "inputs",
        "output_names",
        "prepared",
    )

    def __init__(self):
        self.model_name = ""
        self.model_version = ""
        self.id = ""
        self.parameters: Dict[str, Any] = {}
        # (name, datatype, shape) per input, aligned order with the wire
        self.inputs: List[Tuple[str, str, List[int]]] = []
        self.output_names: List[str] = []
        # server-codec slot: per-template precomputed decode plan (the
        # template is cached, so the plan amortizes to zero per request)
        self.prepared: Any = None


def _decode_input_tensor(buf: bytes, pos: int, end: int):
    """InferInputTensor body -> (name, datatype, shape) or None when the
    tensor carries parameters/contents (fall back to proto)."""
    name = ""
    datatype = ""
    shape: List[int] = []
    while pos < end:
        tag, pos = read_varint(buf, pos)
        if tag == 0x0A:  # name
            n, pos = read_varint(buf, pos)
            name = buf[pos : pos + n].decode("utf-8")
            pos += n
        elif tag == 0x12:  # datatype
            n, pos = read_varint(buf, pos)
            datatype = buf[pos : pos + n].decode("utf-8")
            pos += n
        elif tag == 0x1A:  # shape, packed
            n, pos = read_varint(buf, pos)
            stop = pos + n
            while pos < stop:
                dim, pos = read_varint(buf, pos)
                shape.append(_signed64(dim))
        elif tag == 0x18:  # shape, unpacked element
            dim, pos = read_varint(buf, pos)
            shape.append(_signed64(dim))
        else:
            # per-tensor parameters (shared-memory refs), typed contents,
            # or an unknown field: not the fast shape
            return None
    return name, datatype, shape


def _decode_output_tensor(buf: bytes, pos: int, end: int) -> Optional[str]:
    """InferRequestedOutputTensor body -> name, or None when it carries
    parameters (classification / shared-memory refs)."""
    name = ""
    while pos < end:
        tag, pos = read_varint(buf, pos)
        if tag == 0x0A:
            n, pos = read_varint(buf, pos)
            name = buf[pos : pos + n].decode("utf-8")
            pos += n
        else:
            return None
    return name


def decode_request_prefix(buf: bytes) -> Optional[DecodedInfer]:
    """Parse the metadata fields of a serialized ModelInferRequest
    (everything except ``raw_input_contents``, which the scanner strips
    first). Returns ``None`` for shapes the fast path does not cover."""
    out = DecodedInfer()
    pos = 0
    end = len(buf)
    try:
        while pos < end:
            tag, pos = read_varint(buf, pos)
            if tag == _TAG_INPUTS:
                n, pos = read_varint(buf, pos)
                tensor = _decode_input_tensor(buf, pos, pos + n)
                if tensor is None:
                    return None
                out.inputs.append(tensor)
                pos += n
            elif tag == _TAG_MODEL_NAME:
                n, pos = read_varint(buf, pos)
                out.model_name = buf[pos : pos + n].decode("utf-8")
                pos += n
            elif tag == _TAG_MODEL_VERSION:
                n, pos = read_varint(buf, pos)
                out.model_version = buf[pos : pos + n].decode("utf-8")
                pos += n
            elif tag == _TAG_ID:
                n, pos = read_varint(buf, pos)
                out.id = buf[pos : pos + n].decode("utf-8")
                pos += n
            elif tag == _TAG_PARAMS:
                n, pos = read_varint(buf, pos)
                key, value = _decode_map_entry(buf, pos, pos + n)
                out.parameters[key] = value
                pos += n
            elif tag == _TAG_OUTPUTS:
                n, pos = read_varint(buf, pos)
                name = _decode_output_tensor(buf, pos, pos + n)
                if name is None:
                    return None
                out.output_names.append(name)
                pos += n
            else:
                return None  # unknown field: not the fast shape
    except UnicodeDecodeError:
        raise WireError("non-UTF-8 string field") from None
    return out


# per-request parameters excised from the scanner's cache key (their
# values change every request — keyed raw, they would make ring traffic
# a 100% cache miss AND wholesale-clear hot templates at cache_max)
_EXCISED_PARAM_KEYS = frozenset((b"shm_ring_slot", b"shm_ring_seq"))


class RequestScanner:
    """Memoizing ModelInferRequest scanner.

    ``scan(data)`` walks only the TOP-LEVEL tags (a dozen varints),
    collects ``raw_input_contents`` as zero-copy memoryviews, and looks
    the metadata prefix up in a bounded cache keyed by its exact bytes —
    steady-state cost is the walk plus one dict hit. Per-request fields
    are excised from the cache key and returned separately: the
    top-level ``id`` (unique correlation ids in the multiplexed stream
    mode) and the ``shm_ring_slot``/``shm_ring_seq`` parameters (they
    advance every ring request). A prefix outside the fast shape caches
    as a negative entry so repeated exotic requests don't re-parse
    either.

    The cache is bounded (``cache_max`` distinct prefixes, cleared
    wholesale on overflow) so a hostile client cycling distinct
    metadata cannot grow server memory without bound.
    """

    __slots__ = ("cache_max", "_cache")

    _MISS = object()  # negative cache entry: prefix is not fast-shape

    def __init__(self, cache_max: int = 512):
        self.cache_max = cache_max
        self._cache: Dict[bytes, Any] = {}

    def scan(
        self, data: bytes
    ) -> Optional[
        Tuple[DecodedInfer, str, Optional[Dict[str, Any]], List[memoryview]]
    ]:
        """Returns (metadata template, request id, excised per-request
        parameters or None, raw views) — or None (fall back to the proto
        codec).

        The template is SHARED across requests with the same prefix —
        callers must not mutate it (``template.id`` is always ""; the
        per-request id and the excised parameters ride alongside).
        Raises :class:`WireError` on structurally broken bytes.
        """
        pos = 0
        end = len(data)
        raw_start = -1
        request_id = ""
        excised: List[Tuple[int, int]] = []  # spans cut from the key
        extra_params: Optional[Dict[str, Any]] = None
        raws: List[memoryview] = []
        mv = None
        while pos < end:
            tag = data[pos]
            pos += 1
            if tag >= 0x80:  # multi-byte tag: field > 15, unknown schema
                return None
            if tag == _TAG_RAW:
                if raw_start < 0:
                    raw_start = pos - 1
                n, pos = read_varint(data, pos)
                if mv is None:
                    mv = memoryview(data)
                raws.append(mv[pos : pos + n])
                pos += n
            elif tag in _KNOWN_TAGS:
                if raw_start >= 0:
                    # metadata after raw contents: legal protobuf but not
                    # the serializer order the prefix split assumes
                    return None
                start = pos - 1
                n, pos = read_varint(data, pos)
                content = pos
                pos += n
                if tag == _TAG_ID:
                    try:
                        request_id = data[content:pos].decode("utf-8")
                    except UnicodeDecodeError:
                        raise WireError("non-UTF-8 id field") from None
                    excised.append((start, pos))
                elif (
                    tag == _TAG_PARAMS
                    and n > 2
                    and data[content] == 0x0A
                    and data[content + 1] < 0x80
                    and data[content + 2 : content + 2 + data[content + 1]]
                    in _EXCISED_PARAM_KEYS
                ):
                    try:
                        key, value = _decode_map_entry(data, content, pos)
                    except WireError:
                        return None
                    if extra_params is None:
                        extra_params = {}
                    extra_params[key] = value
                    excised.append((start, pos))
            else:
                return None
        if pos != end:
            raise WireError("truncated message")
        meta_end = raw_start if raw_start >= 0 else end
        if not excised:
            prefix = data[:meta_end]
        else:
            parts = []
            cursor = 0
            for span_start, span_stop in excised:  # in scan order
                parts.append(data[cursor:span_start])
                cursor = span_stop
            parts.append(data[cursor:meta_end])
            prefix = b"".join(parts)
        template = self._cache.get(prefix)
        if template is None:
            template = decode_request_prefix(prefix)
            if len(self._cache) >= self.cache_max:
                self._cache.clear()
            self._cache[prefix] = (
                template if template is not None else self._MISS
            )
        if template is self._MISS or template is None:
            return None
        return template, request_id, extra_params, raws


# -- message builders ---------------------------------------------------------


def _encode_string_field(out: bytearray, tag: int, value: str) -> None:
    """Length-delimited string field; default ("") omitted like proto3."""
    if not value:
        return
    data = value.encode("utf-8")
    out.append(tag)
    write_varint(out, len(data))
    out += data


def _encode_shape(out: bytearray, shape: Sequence[int]) -> None:
    """Packed repeated int64 ``shape`` (field 3); empty omitted."""
    if not shape:
        return
    packed = bytearray()
    for dim in shape:
        write_varint(packed, int(dim) & _U64_MASK)
    out.append(0x1A)
    write_varint(out, len(packed))
    out += packed


def _encode_tensor_meta(
    name: str,
    datatype: str,
    shape: Sequence[int],
    params: Optional[Dict[str, Any]],
) -> bytearray:
    sub = bytearray()
    _encode_string_field(sub, 0x0A, name)
    _encode_string_field(sub, 0x12, datatype)
    _encode_shape(sub, shape)
    if params:
        _encode_params_map(sub, 0x22, params)
    return sub


def encode_infer_response(
    out: bytearray,
    model_name: str,
    model_version: str,
    request_id: str,
    parameters: Optional[Dict[str, Any]],
    outputs: Sequence[Tuple[str, str, Sequence[int], Optional[Dict[str, Any]]]],
    raw_contents: Sequence[Any],
) -> None:
    """Append a serialized ModelInferResponse to ``out``.

    ``outputs`` holds (name, datatype, shape, parameters-or-None) per
    tensor; ``raw_contents`` the aligned raw_output_contents entries
    (bytes-like; every output contributes one, empty for shm outputs).
    """
    _encode_string_field(out, 0x0A, model_name)
    _encode_string_field(out, 0x12, model_version)
    _encode_string_field(out, 0x1A, request_id)
    if parameters:
        _encode_params_map(out, 0x22, parameters)
    for name, datatype, shape, params in outputs:
        sub = _encode_tensor_meta(name, datatype, shape, params)
        out.append(0x2A)
        write_varint(out, len(sub))
        out += sub
    for raw in raw_contents:
        out.append(0x32)
        write_varint(out, len(raw))
        out += raw


def encode_output_meta_block(
    outputs: Sequence[Tuple[str, str, Sequence[int]]]
) -> bytes:
    """The concatenated field-5 (outputs) submessages for a parameterless
    output set — the cacheable middle of a ModelInferResponse."""
    out = bytearray()
    for name, datatype, shape in outputs:
        sub = _encode_tensor_meta(name, datatype, shape, None)
        out.append(0x2A)
        write_varint(out, len(sub))
        out += sub
    return bytes(out)


def encode_head(model_name: str, model_version: str) -> bytes:
    """Fields 1-2 of a ModelInfer message (cacheable per model)."""
    out = bytearray()
    _encode_string_field(out, 0x0A, model_name)
    _encode_string_field(out, 0x12, model_version)
    return bytes(out)


def encode_infer_request(
    out: bytearray,
    model_name: str,
    model_version: str,
    request_id: str,
    parameters: Optional[Dict[str, Any]],
    inputs: Sequence[Tuple[str, str, Sequence[int]]],
    raw_contents: Sequence[Any],
    output_names: Sequence[str] = (),
) -> None:
    """Append a serialized ModelInferRequest to ``out`` (client mirror of
    :func:`encode_infer_response`; inputs are (name, datatype, shape))."""
    _encode_string_field(out, 0x0A, model_name)
    _encode_string_field(out, 0x12, model_version)
    _encode_string_field(out, 0x1A, request_id)
    if parameters:
        _encode_params_map(out, 0x22, parameters)
    for name, datatype, shape in inputs:
        sub = _encode_tensor_meta(name, datatype, shape, None)
        out.append(_TAG_INPUTS)
        write_varint(out, len(sub))
        out += sub
    for name in output_names:
        sub = bytearray()
        _encode_string_field(sub, 0x0A, name)
        out.append(_TAG_OUTPUTS)
        write_varint(out, len(sub))
        out += sub
    for raw in raw_contents:
        out.append(_TAG_RAW)
        write_varint(out, len(raw))
        out += raw


def encode_input_meta_block(
    inputs: Sequence[Tuple[str, str, Sequence[int]]],
    output_names: Sequence[str] = (),
) -> bytes:
    """The concatenated field-5/6 submessages of a ModelInferRequest —
    the cacheable middle for clients resending one tensor signature."""
    out = bytearray()
    for name, datatype, shape in inputs:
        sub = _encode_tensor_meta(name, datatype, shape, None)
        out.append(_TAG_INPUTS)
        write_varint(out, len(sub))
        out += sub
    for name in output_names:
        sub = bytearray()
        _encode_string_field(sub, 0x0A, name)
        out.append(_TAG_OUTPUTS)
        write_varint(out, len(sub))
        out += sub
    return bytes(out)


def encode_stream_response(
    out: bytearray, infer_response: Any = b"", error_message: str = ""
) -> None:
    """Append a serialized ModelStreamInferResponse wrapping an
    already-serialized ModelInferResponse (``infer_response`` bytes-like)
    and/or an in-band ``error_message``. The ``infer_response`` field is
    always emitted (possibly empty) — matching the servicer, which always
    sets the submessage, so presence-sensitive clients see no change."""
    _encode_string_field(out, 0x0A, error_message)
    out.append(0x12)
    write_varint(out, len(infer_response))
    out += infer_response


def decode_infer_request(data):
    """One-shot request decode (tests and one-off callers): a thin
    wrapper over :class:`RequestScanner` — there is exactly one parser.
    Returns (template, request_id, extra_params, raw views) or None."""
    return RequestScanner(cache_max=1).scan(bytes(data))


# -- router splice helpers ----------------------------------------------------
#
# The router tier forwards serialized ModelInfer bytes without ever
# materializing a proto: it rewrites exactly ONE field — the top-level
# ``id`` (field 3 on BOTH ModelInferRequest and ModelInferResponse, the
# correlation key of the multiplexed backend streams) — with a tag walk
# plus bytes slices. Field order is irrelevant to protobuf decoding and
# the server-side RequestScanner excises ``id`` from its cache key, so a
# spliced request still rides the backend's fast path.


def _skip_wire_value(buf, pos: int, wiretype: int) -> int:
    """Advance past one field's value (generic walk: the response side
    may carry fields this module doesn't model)."""
    if wiretype == 0:  # varint
        _, pos = read_varint(buf, pos)
        return pos
    if wiretype == 1:  # fixed64
        return pos + 8
    if wiretype == 2:  # length-delimited
        n, pos = read_varint(buf, pos)
        return pos + n
    if wiretype == 5:  # fixed32
        return pos + 4
    raise WireError(f"unsupported wire type {wiretype}")


def _id_spans(data) -> Tuple[str, List[Tuple[int, int]]]:
    """(decoded id, [(start, stop) of every top-level field-3 entry])
    via one generic top-level walk; last entry wins (protobuf merge)."""
    pos = 0
    end = len(data)
    message_id = ""
    spans: List[Tuple[int, int]] = []
    while pos < end:
        start = pos
        tag, pos = read_varint(data, pos)
        field, wiretype = tag >> 3, tag & 0x7
        if field == 3 and wiretype == 2:
            n, pos = read_varint(data, pos)
            try:
                message_id = bytes(data[pos : pos + n]).decode("utf-8")
            except UnicodeDecodeError:
                raise WireError("non-UTF-8 id field") from None
            pos += n
            spans.append((start, pos))
        else:
            pos = _skip_wire_value(data, pos, wiretype)
    if pos != end:
        raise WireError("truncated message")
    return message_id, spans


def read_message_id(data) -> str:
    """The top-level ``id`` of serialized ModelInferRequest/Response
    bytes (same schema slot both directions — one reader serves the
    router's correlation on requests and responses alike)."""
    message_id, _spans = _id_spans(data)
    return message_id


def splice_message_id(data, new_id: str) -> Tuple[bytes, str]:
    """Serialized ModelInfer{Request,Response} bytes with the top-level
    ``id`` replaced by ``new_id``; returns (spliced bytes, original id).
    No other byte is touched — the rewrite is a prepended id field plus
    the excision of the old spans (prepending keeps metadata ahead of
    the raw contents, so the backend scanner's prefix split still
    applies)."""
    original, spans = _id_spans(data)
    out = bytearray()
    _encode_string_field(out, _TAG_ID, new_id)
    cursor = 0
    for start, stop in spans:
        out += data[cursor:start]
        cursor = stop
    out += data[cursor:]
    return bytes(out), original


def splice_forward_request(data, new_id: str) -> Tuple[bytes, str]:
    """The router's request rewrite in one pass: correlation ``id`` :=
    ``new_id`` and a ``multiplex`` parameter prepended (so the backend
    executes it as its own task on the shared persistent stream instead
    of serializing the stream). Returns (forwarded bytes, original id).
    A client-sent ``multiplex`` entry, if any, appears later in the map
    and wins under protobuf merge — the router never overrides it."""
    original, spans = _id_spans(data)
    out = bytearray()
    _encode_string_field(out, _TAG_ID, new_id)
    _encode_params_map(out, _TAG_PARAMS, {"multiplex": True})
    cursor = 0
    for start, stop in spans:
        out += data[cursor:start]
        cursor = stop
    out += data[cursor:]
    return bytes(out), original


def split_stream_frame(data) -> Tuple[str, Any]:
    """Split serialized ModelStreamInferResponse bytes into
    (error_message, infer_response bytes view) without a proto parse —
    the router's per-frame cost on the response path. The server emits
    exactly one ``infer_response`` per frame; were several present the
    last complete submessage wins (protobuf merge approximation that
    cannot occur with our own server)."""
    pos = 0
    end = len(data)
    error_message = ""
    response: Any = b""
    mv = None
    while pos < end:
        tag, pos = read_varint(data, pos)
        field, wiretype = tag >> 3, tag & 0x7
        if field == 1 and wiretype == 2:  # error_message
            n, pos = read_varint(data, pos)
            try:
                error_message = bytes(data[pos : pos + n]).decode("utf-8")
            except UnicodeDecodeError:
                raise WireError("non-UTF-8 error_message") from None
            pos += n
        elif field == 2 and wiretype == 2:  # infer_response
            n, pos = read_varint(data, pos)
            if mv is None:
                mv = memoryview(data)
            response = mv[pos : pos + n]
            pos += n
        else:
            pos = _skip_wire_value(data, pos, wiretype)
    if pos != end:
        raise WireError("truncated stream frame")
    return error_message, response
