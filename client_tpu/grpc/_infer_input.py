"""InferInput for the gRPC protocol (proto-backed).

Capability parity with reference
src/python/library/tritonclient/grpc/_infer_input.py:36-219, with the
JAX-native ``set_data_from_jax`` addition.
"""

from typing import List, Optional, Sequence

import numpy as np

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.utils import (
    InferenceServerException,
    np_to_triton_dtype,
    serialize_byte_tensor,
)


class InferInput:
    """An input tensor for a gRPC inference request."""

    def __init__(self, name: str, shape: Sequence[int], datatype: str):
        self._input = pb.ModelInferRequest.InferInputTensor(
            name=name, datatype=datatype
        )
        self._input.shape.extend(int(s) for s in shape)
        self._raw_content: Optional[bytes] = None

    def name(self) -> str:
        return self._input.name

    def datatype(self) -> str:
        return self._input.datatype

    def shape(self) -> List[int]:
        return list(self._input.shape)

    def set_shape(self, shape: Sequence[int]) -> "InferInput":
        self._input.ClearField("shape")
        self._input.shape.extend(int(s) for s in shape)
        return self

    def set_data_from_numpy(self, input_tensor: np.ndarray) -> "InferInput":
        """Attach data from a numpy array (always raw bytes on gRPC)."""
        if not isinstance(input_tensor, np.ndarray):
            raise InferenceServerException("input tensor must be a numpy array")
        dtype = np_to_triton_dtype(input_tensor.dtype)
        if dtype is None:
            raise InferenceServerException(
                f"unsupported numpy dtype {input_tensor.dtype}"
            )
        if dtype != self._input.datatype:
            raise InferenceServerException(
                f"got unexpected datatype {dtype} from numpy array; expected "
                f"{self._input.datatype}"
            )
        if list(input_tensor.shape) != list(self._input.shape):
            raise InferenceServerException(
                f"got unexpected numpy array shape {list(input_tensor.shape)}; "
                f"expected {list(self._input.shape)}"
            )
        self._input.parameters.pop("shared_memory_region", None)
        self._input.parameters.pop("shared_memory_byte_size", None)
        self._input.parameters.pop("shared_memory_offset", None)
        if self._input.datatype == "BYTES":
            self._raw_content = serialize_byte_tensor(input_tensor).tobytes()
        else:
            self._raw_content = np.ascontiguousarray(input_tensor).tobytes()
        return self

    def set_data_from_jax(self, jax_array) -> "InferInput":
        """Attach data from a jax.Array (single device-to-host staging)."""
        return self.set_data_from_numpy(np.asarray(jax_array))

    def set_shared_memory(
        self, region_name: str, byte_size: int, offset: int = 0
    ) -> "InferInput":
        """Source this input from a pre-registered shared-memory region."""
        self._raw_content = None
        self._input.ClearField("contents")
        self._input.parameters["shared_memory_region"].string_param = region_name
        self._input.parameters["shared_memory_byte_size"].int64_param = int(
            byte_size
        )
        if offset != 0:
            self._input.parameters["shared_memory_offset"].int64_param = int(
                offset
            )
        return self

    def _get_tensor(self) -> pb.ModelInferRequest.InferInputTensor:
        return self._input

    def _get_raw_content(self) -> Optional[bytes]:
        return self._raw_content
