"""Generated protobuf message modules (see tools/gen_protos.sh)."""

from client_tpu.grpc._generated import model_config_pb2  # noqa: F401
from client_tpu.grpc._generated import grpc_service_pb2  # noqa: F401

# Compatibility aliases matching the reference wheel's module names
# (service_pb2 / model_config_pb2).
service_pb2 = grpc_service_pb2
