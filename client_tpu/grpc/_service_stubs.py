"""Hand-written gRPC stubs/servicer glue for inference.GRPCInferenceService.

grpc_tools (the protoc gRPC plugin) is not available in this environment, so
the thin service-binding layer normally emitted into ``*_pb2_grpc.py`` is
written by hand here. It is equivalent in behavior: a ``Stub`` built from a
channel (works with both ``grpc.Channel`` and ``grpc.aio.Channel``) and an
``add_*_to_server`` registration helper for servicers.

Method surface parity: the 20 RPCs the reference client uses (reference
src/python/library/tritonclient/grpc/_client.py) plus the three
TpuSharedMemory* RPCs of the client_tpu extension.
"""

import grpc

from client_tpu.grpc._generated import grpc_service_pb2 as pb

_SERVICE = "inference.GRPCInferenceService"

# method name -> (kind, request message, response message)
# kind: 'uu' unary-unary, 'ss' stream-stream
_METHODS = {
    "ServerLive": ("uu", pb.ServerLiveRequest, pb.ServerLiveResponse),
    "ServerReady": ("uu", pb.ServerReadyRequest, pb.ServerReadyResponse),
    "ModelReady": ("uu", pb.ModelReadyRequest, pb.ModelReadyResponse),
    "ServerMetadata": ("uu", pb.ServerMetadataRequest, pb.ServerMetadataResponse),
    "ModelMetadata": ("uu", pb.ModelMetadataRequest, pb.ModelMetadataResponse),
    "ModelInfer": ("uu", pb.ModelInferRequest, pb.ModelInferResponse),
    "ModelStreamInfer": ("ss", pb.ModelInferRequest, pb.ModelStreamInferResponse),
    "ModelConfig": ("uu", pb.ModelConfigRequest, pb.ModelConfigResponse),
    "ModelStatistics": ("uu", pb.ModelStatisticsRequest, pb.ModelStatisticsResponse),
    "RepositoryIndex": ("uu", pb.RepositoryIndexRequest, pb.RepositoryIndexResponse),
    "RepositoryModelLoad": (
        "uu",
        pb.RepositoryModelLoadRequest,
        pb.RepositoryModelLoadResponse,
    ),
    "RepositoryModelUnload": (
        "uu",
        pb.RepositoryModelUnloadRequest,
        pb.RepositoryModelUnloadResponse,
    ),
    "SystemSharedMemoryStatus": (
        "uu",
        pb.SystemSharedMemoryStatusRequest,
        pb.SystemSharedMemoryStatusResponse,
    ),
    "SystemSharedMemoryRegister": (
        "uu",
        pb.SystemSharedMemoryRegisterRequest,
        pb.SystemSharedMemoryRegisterResponse,
    ),
    "SystemSharedMemoryUnregister": (
        "uu",
        pb.SystemSharedMemoryUnregisterRequest,
        pb.SystemSharedMemoryUnregisterResponse,
    ),
    "CudaSharedMemoryStatus": (
        "uu",
        pb.CudaSharedMemoryStatusRequest,
        pb.CudaSharedMemoryStatusResponse,
    ),
    "CudaSharedMemoryRegister": (
        "uu",
        pb.CudaSharedMemoryRegisterRequest,
        pb.CudaSharedMemoryRegisterResponse,
    ),
    "CudaSharedMemoryUnregister": (
        "uu",
        pb.CudaSharedMemoryUnregisterRequest,
        pb.CudaSharedMemoryUnregisterResponse,
    ),
    "TpuSharedMemoryStatus": (
        "uu",
        pb.TpuSharedMemoryStatusRequest,
        pb.TpuSharedMemoryStatusResponse,
    ),
    "TpuSharedMemoryRegister": (
        "uu",
        pb.TpuSharedMemoryRegisterRequest,
        pb.TpuSharedMemoryRegisterResponse,
    ),
    "TpuSharedMemoryUnregister": (
        "uu",
        pb.TpuSharedMemoryUnregisterRequest,
        pb.TpuSharedMemoryUnregisterResponse,
    ),
    "TraceSetting": ("uu", pb.TraceSettingRequest, pb.TraceSettingResponse),
    "LogSettings": ("uu", pb.LogSettingsRequest, pb.LogSettingsResponse),
}


class GRPCInferenceServiceStub:
    """Client stub; pass a ``grpc.Channel`` or ``grpc.aio.Channel``."""

    def __init__(self, channel):
        for name, (kind, req, resp) in _METHODS.items():
            factory = channel.unary_unary if kind == "uu" else channel.stream_stream
            setattr(
                self,
                name,
                factory(
                    f"/{_SERVICE}/{name}",
                    request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString,
                ),
            )


class GRPCInferenceServiceServicer:
    """Server-side base class; override the RPC methods you implement."""

    def _unimplemented(self, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        context.set_details("Method not implemented")
        raise NotImplementedError("Method not implemented")


def _make_default(name):
    def handler(self, request, context):
        self._unimplemented(context)

    handler.__name__ = name
    return handler


for _name in _METHODS:
    setattr(GRPCInferenceServiceServicer, _name, _make_default(_name))


def add_GRPCInferenceServiceServicer_to_server(servicer, server):
    """Register a servicer.

    A servicer that sets ``raw_infer_bytes = True`` receives the two
    inference methods (ModelInfer / ModelStreamInfer) as RAW serialized
    bytes and must return serialized response bytes — the protobuf-free
    wire fast path (client_tpu.grpc._wire). Every other method keeps the
    proto (de)serializers.
    """
    raw_infer = bool(getattr(servicer, "raw_infer_bytes", False))
    handlers = {}
    for name, (kind, req, resp) in _METHODS.items():
        make = (
            grpc.unary_unary_rpc_method_handler
            if kind == "uu"
            else grpc.stream_stream_rpc_method_handler
        )
        if raw_infer and name in ("ModelInfer", "ModelStreamInfer"):
            handlers[name] = make(
                getattr(servicer, name),
                request_deserializer=None,
                response_serializer=None,
            )
        else:
            handlers[name] = make(
                getattr(servicer, name),
                request_deserializer=req.FromString,
                response_serializer=resp.SerializeToString,
            )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
    )
