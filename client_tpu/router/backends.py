"""Backend transport for the router tier: persistent raw-byte streams.

One :class:`BackendLink` per replica holds a single long-lived
``ModelStreamInfer`` stream carrying RAW serialized bytes both ways
(identity (de)serializers — the same wire fast path the PR-11 client mux
and the server's ``raw_infer_bytes`` servicer use). Forwarding a request
is one ``write()``; the reader loop splits each response frame with
:func:`client_tpu.grpc._wire.split_stream_frame` and dispatches it by
the router's correlation id — no protobuf object is ever built on the
proxy hot path.

A dead stream (replica restart, UNAVAILABLE) fails every in-flight sink
with a retryable error and the next send opens a fresh stream — the
router-side mirror of the client mux's reconnect-on-UNAVAILABLE.

:class:`ReadinessProber` keeps the router's endpoint pool and
model→replica table fresh: per interval it asks every backend
``ServerReady`` (the gRPC face of ``/v2/health/ready`` — a draining
replica answers not-ready, PR-5 semantics) and, when ready,
``RepositoryIndex`` for the models it serves.
"""

import asyncio
from typing import Any, Callable, Dict, Optional, Tuple

import grpc

from client_tpu.grpc import _wire as wire
from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._mux import _STREAM_METHOD
from client_tpu.grpc._service_stubs import GRPCInferenceServiceStub
from client_tpu.grpc._utils import rpc_error_to_exception
from client_tpu.utils import InferenceServerException

_MAX_MESSAGE = 2**31 - 1  # INT32_MAX, both directions (server parity)

_DEFAULT_OPTIONS = (
    ("grpc.max_send_message_length", _MAX_MESSAGE),
    ("grpc.max_receive_message_length", _MAX_MESSAGE),
    ("grpc.primary_user_agent", "client-tpu-router"),
)


def _identity(data):
    return data


class BackendLink:
    """One backend replica: a shared channel, a proto stub for the
    control-plane RPCs (probes, metadata proxying), and one persistent
    raw-bytes inference stream.

    Sinks are ``callback(error_message, response_bytes, failure)``:
    exactly one of ``response_bytes`` (a frame for this id) or
    ``failure`` (an :class:`InferenceServerException` when the stream
    died) is meaningful per call. Unary sends register a one-shot future
    sink; the stream front registers a long-lived queue sink and
    receives EVERY frame with its id (decoupled models emit many).
    """

    def __init__(
        self,
        url: str,
        channel_factory: Optional[Callable[[str], Any]] = None,
    ):
        self.url = url
        if channel_factory is None:
            self._channel = grpc.aio.insecure_channel(
                url, options=list(_DEFAULT_OPTIONS)
            )
        else:
            self._channel = channel_factory(url)
        self.stub = GRPCInferenceServiceStub(self._channel)
        self._method = self._channel.stream_stream(
            _STREAM_METHOD,
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        self._call = None
        self._reader: Optional[asyncio.Task] = None
        self._write_lock = asyncio.Lock()
        # rid -> sink; one-shot sinks are removed on first frame by the
        # reader, long-lived (stream-front) sinks stay until unregister
        self._sinks: Dict[str, Tuple[Callable, bool]] = {}
        self._closed = False
        self.retiring = False  # autoscaler scale-in: drain, don't feed

    # -- stream lifecycle ----------------------------------------------------

    def _ensure_open(self) -> None:
        if self._closed:
            raise InferenceServerException(
                f"backend link {self.url} is closed",
                status="StatusCode.UNAVAILABLE",
            )
        if self._call is None:
            call = self._method()
            self._call = call
            self._reader = asyncio.ensure_future(self._read_loop(call))

    async def _read_loop(self, call) -> None:
        try:
            while True:
                frame = await call.read()
                if frame is grpc.aio.EOF:
                    self._fail_sinks(
                        InferenceServerException(
                            f"backend stream {self.url} closed by the server",
                            status="StatusCode.UNAVAILABLE",
                        )
                    )
                    return
                try:
                    error_message, response = wire.split_stream_frame(frame)
                    rid = wire.read_message_id(response)
                except wire.WireError:
                    continue  # unparseable frame: nothing to correlate
                if not rid:
                    # an error the backend could not correlate: no single
                    # sink owns it — fail everything retryably rather
                    # than hang one forever (mux parity)
                    if error_message:
                        self._fail_sinks(
                            InferenceServerException(
                                error_message,
                                status="StatusCode.UNAVAILABLE",
                            )
                        )
                    continue
                entry = self._sinks.get(rid)
                if entry is None:
                    continue
                sink, long_lived = entry
                if not long_lived:
                    self._sinks.pop(rid, None)
                sink(error_message, bytes(response), None)
        except asyncio.CancelledError:
            self._fail_sinks(
                InferenceServerException(
                    f"backend stream {self.url} closed",
                    status="StatusCode.CANCELLED",
                )
            )
            raise
        except grpc.RpcError as e:
            self._fail_sinks(rpc_error_to_exception(e))
        except Exception as e:  # noqa: BLE001 - surface to waiters
            self._fail_sinks(InferenceServerException(str(e)))
        finally:
            if self._call is call:
                self._call = None
                self._reader = None

    def _fail_sinks(self, error: InferenceServerException) -> None:
        sinks, self._sinks = self._sinks, {}
        for sink, _long_lived in sinks.values():
            sink(None, None, error)

    # -- sends ---------------------------------------------------------------

    def register(self, rid: str, sink: Callable, long_lived: bool = False):
        self._sinks[rid] = (sink, long_lived)

    def unregister(self, rid: str) -> None:
        self._sinks.pop(rid, None)

    async def write(self, payload: bytes) -> None:
        """Forward one already-spliced request frame (a sink for its id
        must be registered FIRST — the response may race the return)."""
        self._ensure_open()
        call = self._call
        try:
            async with self._write_lock:
                await call.write(payload)
        except grpc.RpcError as e:
            raise rpc_error_to_exception(e) from None
        except Exception as e:  # noqa: BLE001 - a dying call object
            raise InferenceServerException(
                f"backend write to {self.url} failed: {e}",
                status="StatusCode.UNAVAILABLE",
            ) from None

    async def unary(
        self, payload: bytes, rid: str, timeout: Optional[float] = None
    ) -> Tuple[str, bytes]:
        """One request → its first (and for unary models only) response
        frame: ``(error_message, response_bytes)``. Stream death raises
        the retryable failure instead."""
        loop = asyncio.get_running_loop()
        future: "asyncio.Future" = loop.create_future()

        def sink(error_message, response, failure):
            if future.done():
                return
            if failure is not None:
                future.set_exception(failure)
            else:
                future.set_result((error_message, response))

        self.register(rid, sink)
        try:
            await self.write(payload)
            if timeout is not None:
                return await asyncio.wait_for(future, timeout)
            return await future
        finally:
            self.unregister(rid)

    @property
    def pending(self) -> int:
        return len(self._sinks)

    async def close(self) -> None:
        self._closed = True
        if self._reader is not None:
            self._reader.cancel()
            try:
                await self._reader
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._reader = None
        self._call = None
        try:
            await self._channel.close()
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass


class ReadinessProber:
    """Periodic backend health + model-inventory probes.

    Drives the pool's bench/recover transitions exactly like a client
    surface does: a not-ready or unreachable backend is marked down for
    ``2 * interval_s`` (so it stays benched between probes), and a
    benched backend that answers ready again re-enters through
    :meth:`EndpointPool.mark_up` — but only once its cooldown elapsed
    (``needs_probe``), so a deliberate ejection is never overridden
    early. A ready backend also refreshes the model→replica table from
    ``RepositoryIndex`` (ready models only).
    """

    def __init__(
        self,
        core,
        links: Dict[str, BackendLink],
        interval_s: float = 0.5,
        probe_timeout_s: float = 1.0,
    ):
        self.core = core  # RouterCore: pool + table
        self.links = links
        self.interval_s = interval_s
        self.probe_timeout_s = probe_timeout_s
        self._task: Optional[asyncio.Task] = None

    async def probe_once(self) -> None:
        pool = self.core.pool
        for ep in pool.endpoints:
            link = self.links.get(ep.url)
            if link is None or link.retiring:
                continue
            ready = False
            models = None
            try:
                response = await link.stub.ServerReady(
                    pb.ServerReadyRequest(), timeout=self.probe_timeout_s
                )
                ready = bool(response.ready)
                if ready:
                    index = await link.stub.RepositoryIndex(
                        pb.RepositoryIndexRequest(ready=True),
                        timeout=self.probe_timeout_s,
                    )
                    models = [m.name for m in index.models]
            except Exception:  # noqa: BLE001 - unreachable == not ready
                ready = False
            if ready:
                if models is not None:
                    self.core.table.set_backend_models(ep.url, models)
                if pool.needs_probe(ep):
                    pool.mark_up(ep)
            elif ep.state(self.core.now()) in ("up", "probe"):
                pool.mark_down(ep, cooldown_s=2 * self.interval_s)

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.probe_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - probing must not die
                pass

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
