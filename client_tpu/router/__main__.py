"""``python -m client_tpu.router --serve`` — one router as a subprocess.

The process form of :class:`client_tpu.router.RouterServer`: bench
drivers spawn it in front of fleet replicas (router-vs-direct proxy
tax), and the chaos tests SIGKILL it mid-run to prove clients with
``urls=[router_a, router_b]`` fail over with zero visible errors.

Backends come from ``--backends`` (``grpc[=http]`` comma list) and/or
``--replica-ports-file`` (repeatable; each is the JSON a ``python -m
client_tpu.perf.fleet_runner --serve --ports-file`` replica wrote — the
same file handoff, chained). The router's own bound ports go to
``--ports-file`` (atomic) and stdout.
"""

import argparse
import json
import signal
import threading
from typing import Dict, List, Optional

from client_tpu.perf.fleet_runner import read_ports_file, write_ports_file
from client_tpu.router.server import RouterServer


def _parse_backends(spec: str) -> Dict[str, Optional[str]]:
    """``grpc_addr[=http_addr],...`` → {grpc: http_or_None}."""
    backends: Dict[str, Optional[str]] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        grpc_addr, _, http_addr = item.partition("=")
        backends[grpc_addr] = http_addr or None
    return backends


def _backends_from_ports_files(
    paths: List[str], host: str, wait_s: float
) -> Dict[str, Optional[str]]:
    import time as _time

    backends: Dict[str, Optional[str]] = {}
    poll_s = 0.05
    for path in paths:
        ports = read_ports_file(path)
        attempts = max(1, int(wait_s / poll_s))
        while ports is None and attempts > 0:
            _time.sleep(poll_s)
            attempts -= 1
            ports = read_ports_file(path)
        if ports is None:
            raise SystemExit(f"no ports file at {path} after {wait_s:g}s")
        grpc_port = ports.get("grpc_port")
        http_port = ports.get("http_port")
        if not grpc_port:
            raise SystemExit(f"{path}: replica exposes no gRPC port")
        backends[f"{host}:{grpc_port}"] = (
            f"{host}:{http_port}" if http_port else None
        )
    return backends


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m client_tpu.router",
        description="serve one router over a set of fleet replicas "
        "(prints a JSON ports line, stops on SIGTERM)",
    )
    parser.add_argument("--serve", action="store_true", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--http-port", type=int, default=0)
    parser.add_argument("--grpc-port", type=int, default=0)
    parser.add_argument(
        "--backends",
        default="",
        help="comma list of backend addresses, each 'grpc[=http]'",
    )
    parser.add_argument(
        "--replica-ports-file",
        action="append",
        default=[],
        metavar="PATH",
        help="read one backend's ports from a fleet_runner --ports-file "
        "JSON (repeatable)",
    )
    parser.add_argument(
        "--backend-host",
        default="127.0.0.1",
        help="host the --replica-ports-file ports bind on",
    )
    parser.add_argument("--ports-file", default=None, metavar="PATH")
    parser.add_argument(
        "--policy",
        default="least_outstanding",
        help="routing policy (round_robin / least_outstanding / p2c / "
        "consistent_hash)",
    )
    parser.add_argument(
        "--max-inflight",
        type=int,
        default=0,
        help="shed default-priority requests past this many in flight "
        "(0 = no shedding)",
    )
    parser.add_argument("--probe-interval", type=float, default=0.25)
    parser.add_argument("--backend-wait", type=float, default=15.0)
    args = parser.parse_args(argv)

    backends: Dict[str, Optional[str]] = {}
    if args.backends:
        backends.update(_parse_backends(args.backends))
    if args.replica_ports_file:
        backends.update(
            _backends_from_ports_files(
                args.replica_ports_file, args.backend_host, args.backend_wait
            )
        )
    if not backends:
        parser.error("need --backends and/or --replica-ports-file")

    server = RouterServer(
        backends,
        host=args.host,
        http_port=args.http_port,
        grpc_port=args.grpc_port,
        routing_policy=args.policy,
        max_inflight=args.max_inflight,
        probe_interval_s=args.probe_interval,
    )
    server.start()
    ports = {"http_port": server.http_port, "grpc_port": server.grpc_port}
    if args.ports_file:
        write_ports_file(args.ports_file, ports)
    print(json.dumps(ports), flush=True)
    stop = threading.Event()
    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, lambda *_: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
