"""Router core: the transport-agnostic routing brain.

The router tier is a thin, stateless front door: it never materializes a
protobuf on the hot path. Each inference request arrives as serialized
``ModelInferRequest`` bytes, is classified by the same memoizing
:class:`~client_tpu.grpc._wire.RequestScanner` the server runs (model
name, affinity key, priority — one top-level tag walk plus a dict hit),
gets its ``id`` spliced to a router correlation id, and is written onto
a persistent multiplexed backend stream. The response comes back, has
its original id restored, and is forwarded — two id splices of proxy
tax, no (de)serialization.

Everything the PR-7 client learned about fleets runs HERE, server-side,
on the router's own live telemetry: the
:class:`~client_tpu.lifecycle.pool.EndpointPool` (routing policies,
outlier ejection, consistent-hash affinity over per-backend
outstanding/EWMA), the :class:`~client_tpu.lifecycle.hedge.HedgePolicy`
tail-cutter, and UNAVAILABLE failover. A client pointing at ONE router
url installs no failover policy of its own (auto-failover requires a
multi-url pool), so backend failures MUST be absorbed at this layer for
scale events to stay client-invisible.

Overload backstop (PR-4 semantics, moved to the front door): when the
router's in-flight count hits ``max_inflight``, default-priority
requests are shed with RESOURCE_EXHAUSTED and a ``retry_after_s`` hint;
protected traffic (``priority == 1``, the queue policy's highest level)
is always admitted so its p99 stays bounded while the autoscaler
catches up.
"""

import asyncio
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from client_tpu.grpc import _wire as wire
from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._mux import _inband_error
from client_tpu.grpc._utils import is_sequence_request, request_routing_key
from client_tpu.lifecycle.hedge import HedgePolicy, hedged_send_async
from client_tpu.lifecycle.pool import EndpointPool, status_is_unavailable
from client_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from client_tpu.router.backends import BackendLink
from client_tpu.utils import InferenceServerException

# the queue policy's highest priority level (1 = highest, 0 = default);
# protected traffic is never shed at the router
PROTECTED_PRIORITY = 1

# proxy-latency buckets: the router adds ~µs-ms, not the server's
# device-scale seconds
_PROXY_BUCKETS = (
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
)


class RouterOverloadError(InferenceServerException):
    """The router shed this request (admission control, not a backend
    failure). The message deliberately reads "queue full" so stream-mode
    clients derive RESOURCE_EXHAUSTED from the in-band frame, and
    ``retry_after_s`` rides to the client's backoff floor (trailing
    metadata on gRPC, ``Retry-After`` header on HTTP)."""

    def __init__(self, inflight: int, limit: int, retry_after_s: float):
        super().__init__(
            f"router admission queue full: {inflight} in flight "
            f"(limit {limit}); retry after {retry_after_s:g}s",
            status="StatusCode.RESOURCE_EXHAUSTED",
        )
        self.retry_after_s = retry_after_s


class ModelTable:
    """model name → the backend urls currently advertising it ready.

    Refreshed by the readiness prober from each backend's
    ``RepositoryIndex(ready=True)``. Lookup is PERMISSIVE: a model no
    backend has advertised yet (cold start, brand-new replica) resolves
    to None — route anywhere and let the backend answer — so the table
    narrows routing when it knows better and never blackholes traffic
    when it doesn't.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_backend: Dict[str, frozenset] = {}

    def set_backend_models(self, url: str, models) -> None:
        with self._lock:
            self._by_backend[url] = frozenset(models)

    def drop_backend(self, url: str) -> None:
        with self._lock:
            self._by_backend.pop(url, None)

    def urls_for(self, model_name: str) -> Optional[Set[str]]:
        with self._lock:
            urls = {
                url
                for url, models in self._by_backend.items()
                if model_name in models
            }
        return urls or None

    def models(self) -> Set[str]:
        with self._lock:
            out: Set[str] = set()
            for models in self._by_backend.values():
                out |= models
            return out

    def snapshot(self) -> Dict[str, List[str]]:
        with self._lock:
            return {
                url: sorted(models)
                for url, models in self._by_backend.items()
            }


class RouterCore:
    """Routing state + forward orchestration, shared by both protocol
    fronts. Construct inside the event loop that will run the forwards
    (backend links hold aio channels).

    ``backends`` maps each backend's gRPC address to its HTTP address
    (None when the fleet runs gRPC-only — the HTTP proxy then 503s).
    """

    def __init__(
        self,
        backends: Dict[str, Optional[str]],
        routing_policy="least_outstanding",
        hedge: Optional[HedgePolicy] = None,
        max_inflight: int = 0,
        shed_retry_after_s: float = 0.25,
        attempt_timeout_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        logger=None,
        channel_factory: Optional[Callable[[str], Any]] = None,
        link_factory: Callable[..., BackendLink] = BackendLink,
    ):
        if not backends:
            raise ValueError("router needs at least one backend")
        self._clock = clock
        self.pool = EndpointPool(
            list(backends),
            routing_policy=routing_policy,
            clock=clock,
            logger=logger,
        )
        self.http_urls: Dict[str, Optional[str]] = dict(backends)
        self.hedge = hedge
        self.max_inflight = max_inflight
        self.shed_retry_after_s = shed_retry_after_s
        self.attempt_timeout_s = attempt_timeout_s
        self.logger = logger
        self.table = ModelTable()
        self.scanner = wire.RequestScanner()
        self.links: Dict[str, BackendLink] = {}
        self._channel_factory = channel_factory
        self._link_factory = link_factory
        self._rid = itertools.count(1)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.metrics = MetricsRegistry()
        self.m_requests = Counter(
            "tpu_router_requests_total",
            "Requests through the router by protocol and outcome.",
            labelnames=("protocol", "outcome"),
            registry=self.metrics,
        )
        self.m_shed = Counter(
            "tpu_router_shed_total",
            "Requests shed by router admission control, by priority class.",
            labelnames=("priority",),
            registry=self.metrics,
        )
        self.m_retries = Counter(
            "tpu_router_backend_retries_total",
            "Forwards retried on another backend after UNAVAILABLE.",
            registry=self.metrics,
        )
        self.m_proxy = Histogram(
            "tpu_router_proxy_seconds",
            "End-to-end router forward latency (includes backend time).",
            buckets=_PROXY_BUCKETS,
            registry=self.metrics,
        )
        self.g_backends = Gauge(
            "tpu_router_backends",
            "Pool membership by health state.",
            labelnames=("state",),
            registry=self.metrics,
        )
        self.g_inflight = Gauge(
            "tpu_router_inflight",
            "Requests currently being forwarded through the router.",
            registry=self.metrics,
        )
        self.metrics.add_collect_hook(self._collect)

    # -- observability -------------------------------------------------------

    def now(self) -> float:
        return self._clock()

    def _collect(self) -> None:
        states = {"up": 0, "down": 0, "ejected": 0, "probe": 0}
        now = self._clock()
        for ep in self.pool.endpoints:
            state = ep.state(now)
            states[state] = states.get(state, 0) + 1
        for state, count in states.items():
            self.g_backends.labels(state).set(count)
        self.g_inflight.set(self._inflight)

    def snapshot(self) -> dict:
        return {
            "pool": self.pool.snapshot(),
            "models": self.table.snapshot(),
            "inflight": self._inflight,
            "max_inflight": self.max_inflight,
            "backends": {
                url: {"http": http_url}
                for url, http_url in self.http_urls.items()
            },
        }

    # -- membership (autoscaler-driven) --------------------------------------

    def add_backend(self, grpc_url: str, http_url: Optional[str] = None):
        """A replica joined the fleet: route to it as soon as the prober
        sees it ready (it enters the pool up — the first failed forward
        benches it, exactly like a seed backend)."""
        self.http_urls[grpc_url] = http_url
        return self.pool.add_endpoint(grpc_url)

    def remove_backend(self, grpc_url: str) -> Optional[BackendLink]:
        """A replica is about to drain: pull it from routing FIRST (this
        is what makes scale-in dropless — no new request targets it
        while it finishes its in-flights). Returns the retiring link;
        the caller closes it once the replica is gone."""
        if not self.pool.remove_endpoint(grpc_url):
            return None
        self.table.drop_backend(grpc_url)
        self.http_urls.pop(grpc_url, None)
        link = self.links.pop(grpc_url, None)
        if link is not None:
            link.retiring = True
        return link

    def link_for(self, url: str) -> BackendLink:
        link = self.links.get(url)
        if link is None:
            link = self._link_factory(url, self._channel_factory)
            self.links[url] = link
        return link

    async def close(self) -> None:
        links, self.links = list(self.links.values()), {}
        for link in links:
            await link.close()

    # -- admission (overload backstop) ---------------------------------------

    def admit(self, priority: int) -> None:
        """Count one request in; raises :class:`RouterOverloadError` for
        default-priority traffic past ``max_inflight``. Protected
        traffic (priority 1) is always admitted — shedding exists to
        keep ITS latency bounded through overload."""
        with self._inflight_lock:
            if (
                self.max_inflight
                and priority != PROTECTED_PRIORITY
                and self._inflight >= self.max_inflight
            ):
                self.m_shed.labels("default").inc()
                raise RouterOverloadError(
                    self._inflight,
                    self.max_inflight,
                    self.shed_retry_after_s,
                )
            self._inflight += 1

    def release(self) -> None:
        with self._inflight_lock:
            if self._inflight > 0:
                self._inflight -= 1

    # -- request classification ----------------------------------------------

    def classify(self, data) -> Tuple[str, Any, int, bool]:
        """(model_name, routing_key, priority, is_sequence) of serialized
        request bytes — the scanner's top-level walk on the fast shape, a
        one-shot proto parse otherwise. Unparseable bytes classify as
        anonymous default-priority (the backend will reject them with a
        real error message)."""
        key_parameter = self.pool.key_parameter
        try:
            scanned = self.scanner.scan(bytes(data))
        except wire.WireError:
            return "", None, 0, False
        if scanned is not None:
            template, _request_id, _extra, _raws = scanned
            params = template.parameters
            key = params.get(key_parameter) if key_parameter else None
            priority = params.get("priority", 0)
            sequence = bool(params.get("sequence_id"))
            return (
                template.model_name,
                key,
                int(priority) if isinstance(priority, int) else 0,
                sequence,
            )
        try:
            request = pb.ModelInferRequest.FromString(bytes(data))
        except Exception:  # noqa: BLE001 - backend owns the rejection
            return "", None, 0, False
        key = request_routing_key(request, key_parameter)
        priority = 0
        if "priority" in request.parameters:
            priority = int(request.parameters["priority"].uint64_param)
        return request.model_name, key, priority, is_sequence_request(request)

    def next_rid(self) -> str:
        return f"r{next(self._rid)}"

    # -- forwarding ----------------------------------------------------------

    async def _attempt(self, ep, timeout: Optional[float], data) -> bytes:
        """One raw forward against a SPECIFIC backend: splice, write,
        await the correlated frame, restore the original id. Raises
        :class:`InferenceServerException` (in-band backend errors get
        their status derived from the message text — same mapping the
        client mux applies — so drain/queue-full stay retryable)."""
        link = self.link_for(ep.url)
        rid = self.next_rid()
        payload, original = wire.splice_forward_request(data, rid)
        try:
            error_message, response = await link.unary(payload, rid, timeout)
        except asyncio.TimeoutError:
            raise InferenceServerException(
                f"backend {ep.url} timed out after {timeout}s",
                status="StatusCode.DEADLINE_EXCEEDED",
            ) from None
        if error_message:
            raise _inband_error(error_message)
        spliced, _rid = wire.splice_message_id(response, original)
        return spliced

    async def forward_unary(
        self,
        data,
        protocol: str = "grpc",
        timeout: Optional[float] = None,
    ) -> bytes:
        """Serialized ModelInferRequest bytes in, serialized
        ModelInferResponse bytes out. Owns admission, backend selection,
        UNAVAILABLE failover (and hedging when armed), and the pool's
        begin/finish/observe telemetry brackets."""
        model_name, key, priority, sequence = self.classify(data)
        try:
            self.admit(priority)
        except RouterOverloadError:
            self.m_requests.labels(protocol, "shed").inc()
            raise
        started_total = self._clock()
        outcome = "error"
        try:
            result = await self._forward_admitted(
                data, key, sequence, self.table.urls_for(model_name), timeout
            )
            outcome = "ok"
            return result
        finally:
            self.release()
            self.m_proxy.observe(self._clock() - started_total)
            self.m_requests.labels(protocol, outcome).inc()

    async def _forward_admitted(
        self,
        data,
        key,
        sequence: bool,
        allow: Optional[Set[str]],
        timeout: Optional[float],
    ) -> bytes:
        if timeout is None:
            timeout = self.attempt_timeout_s
        # sequence requests are non-idempotent: never auto-retried,
        # never hedged (mirrors the client surfaces)
        max_attempts = 1 if sequence else max(2, self.pool.size)
        exclude = None
        for attempt in range(max_attempts):
            if self.hedge is not None and not sequence:
                # the hedge orchestration owns the telemetry brackets; a
                # hedged failure skips explicit mark_down (the bracket's
                # error count and the prober converge on the bench)
                async def _pick(_timeout, exclude_ep):
                    return self.pool.pick(
                        key=key, exclude=exclude_ep, allow=allow
                    )

                try:
                    return await hedged_send_async(
                        self.pool,
                        self.hedge,
                        _pick,
                        lambda ep, t: self._attempt(ep, t, data),
                        timeout,
                    )
                except InferenceServerException as exc:
                    if (
                        attempt + 1 < max_attempts
                        and status_is_unavailable(exc.status())
                        and self.pool.has_alternative(None)
                    ):
                        self.m_retries.inc()
                        continue
                    raise
            ep = self.pool.pick(key=key, exclude=exclude, allow=allow)
            started = self.pool.begin(ep)
            try:
                result = await self._attempt(ep, timeout, data)
            except InferenceServerException as exc:
                token = exc.status()
                self.pool.finish(ep, started, ok=False, token=token)
                self.pool.observe(
                    ep,
                    ok=False,
                    token=token,
                    retry_after_s=getattr(exc, "retry_after_s", None),
                )
                if (
                    attempt + 1 < max_attempts
                    and status_is_unavailable(token)
                    and self.pool.has_alternative(ep)
                ):
                    self.m_retries.inc()
                    exclude = ep
                    continue
                raise
            self.pool.finish(ep, started, ok=True)
            self.pool.observe(ep, ok=True)
            return result
        raise InferenceServerException(  # pragma: no cover - loop always
            "router retry loop exhausted",  # returns or raises above
            status="StatusCode.UNAVAILABLE",
        )

    # -- stream front support ------------------------------------------------

    def pick_stream_backend(self, data):
        """The backend a new client stream pins to, chosen from the
        first request's classification (streams keep strict ordering and
        sequence affinity by living on ONE backend, mirroring the client
        mux's pinned-stream semantics)."""
        model_name, key, _priority, _sequence = self.classify(data)
        return self.pool.pick(key=key, allow=self.table.urls_for(model_name))
