"""Router front-ends: gRPC + HTTP over one :class:`RouterCore`.

Both protocol fronts are THIN — the gRPC servicer registers with
``raw_infer_bytes = True`` so inference requests arrive and leave as
serialized bytes (the router never builds a proto on the hot path), and
the HTTP front is a byte-level reverse proxy. Health endpoints are
answered locally (the router's readiness is "≥1 healthy backend", so a
client pool of routers benches a router whose whole fleet is gone);
control-plane metadata RPCs proxy to a healthy backend with the same
UNAVAILABLE failover the data path gets.

:class:`RouterServer` runs both fronts on a background event loop in a
daemon thread — the same harness shape as
:class:`client_tpu.testing.InProcessServer`, so tests and the ``python
-m client_tpu.router`` CLI share one lifecycle.
"""

import asyncio
import json
import threading
from typing import Dict, Optional, Sequence, Tuple

import grpc

from client_tpu.grpc import _wire as wire
from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._service_stubs import (
    _METHODS,
    GRPCInferenceServiceServicer,
    add_GRPCInferenceServiceServicer_to_server,
)
from client_tpu.grpc._utils import rpc_error_to_exception
from client_tpu.lifecycle.pool import status_is_unavailable
from client_tpu.router.core import RouterCore, RouterOverloadError
from client_tpu.utils import InferenceServerException

MAX_GRPC_MESSAGE_SIZE = 2**31 - 1  # INT32_MAX, both directions

_STATUS_BY_TOKEN = {f"StatusCode.{code.name}": code for code in grpc.StatusCode}

# hop-by-hop headers never cross a proxy (RFC 9110 §7.6.1)
_HOP_HEADERS = frozenset(
    (
        "connection",
        "keep-alive",
        "proxy-authenticate",
        "proxy-authorization",
        "te",
        "trailers",
        "transfer-encoding",
        "upgrade",
        "host",
        "content-length",
    )
)


def _grpc_code_for(token: Optional[str]) -> grpc.StatusCode:
    if token in _STATUS_BY_TOKEN:
        return _STATUS_BY_TOKEN[token]
    if status_is_unavailable(token):
        return grpc.StatusCode.UNAVAILABLE
    return grpc.StatusCode.INTERNAL


def _stream_error_frame(message: str, request_id: str) -> bytes:
    """An in-band ModelStreamInferResponse error whose inner response
    carries the CLIENT's request id — error frames stay correlatable on
    multiplexed client streams (server parity)."""
    inner, _ = wire.splice_message_id(b"", request_id)
    out = bytearray()
    wire.encode_stream_response(out, inner, message)
    return bytes(out)


# control-plane RPCs forwarded verbatim to a healthy backend
_PROXIED_METHODS = (
    "ServerMetadata",
    "ModelMetadata",
    "ModelConfig",
    "ModelStatistics",
    "RepositoryIndex",
)


class _RouterServicer(GRPCInferenceServiceServicer):
    """gRPC front: raw-bytes inference forwarding + local health."""

    raw_infer_bytes = True

    def __init__(self, router: RouterCore, proxy_timeout_s: float = 5.0):
        self.router = router
        self.proxy_timeout_s = proxy_timeout_s
        self.draining = False

    # -- inference (raw serialized bytes in/out) -----------------------------

    async def ModelInfer(self, request_bytes, context):
        router = self.router
        try:
            return await router.forward_unary(request_bytes, protocol="grpc")
        except RouterOverloadError as e:
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                e.message(),
                trailing_metadata=(("retry-after", f"{e.retry_after_s:g}"),),
            )
        except InferenceServerException as e:
            await context.abort(_grpc_code_for(e.status()), e.message())

    async def ModelStreamInfer(self, request_iterator, context):
        """Client stream front. The whole client stream pins to ONE
        backend at its first request (strict ordering and sequence
        affinity live on a single replica — the client mux's own
        pinned-stream semantics); frames are forwarded with spliced
        correlation ids and restored per response frame, N frames per
        request supported (decoupled models). Admission is bracketed
        from forward to FIRST response frame. A backend stream death
        surfaces as per-request in-band UNAVAILABLE errors — retryable
        under the client's derived-status mapping, never a hung stream.
        """
        router = self.router
        out_q: "asyncio.Queue" = asyncio.Queue()
        DONE = ("done",)
        rids: Dict[str, str] = {}  # router rid -> client's original id
        admitted = set()  # rids still holding an admission slot
        state = {"ep": None, "link": None}

        def sink_for(rid):
            def sink(error_message, response, failure):
                out_q.put_nowait(("frame", rid, error_message, response, failure))

            return sink

        async def reader() -> None:
            try:
                async for data in request_iterator:
                    try:
                        original = wire.read_message_id(data)
                    except wire.WireError as e:
                        await out_q.put(
                            ("error", "", InferenceServerException(str(e)))
                        )
                        continue
                    model_name, key, priority, _seq = router.classify(data)
                    try:
                        router.admit(priority)
                    except RouterOverloadError as e:
                        router.m_requests.labels("grpc_stream", "shed").inc()
                        await out_q.put(("error", original, e))
                        continue
                    if state["ep"] is None:
                        ep = router.pool.pick(
                            key=key, allow=router.table.urls_for(model_name)
                        )
                        router.pool.pin_stream(ep)
                        state["ep"] = ep
                        state["link"] = router.link_for(ep.url)
                    rid = router.next_rid()
                    payload, _orig = wire.splice_forward_request(data, rid)
                    link = state["link"]
                    rids[rid] = original
                    admitted.add(rid)
                    link.register(rid, sink_for(rid), long_lived=True)
                    try:
                        await link.write(payload)
                    except InferenceServerException as e:
                        link.unregister(rid)
                        rids.pop(rid, None)
                        if rid in admitted:
                            admitted.discard(rid)
                            router.release()
                        router.m_requests.labels(
                            "grpc_stream", "error"
                        ).inc()
                        await out_q.put(("error", original, e))
                        continue
                    router.m_requests.labels("grpc_stream", "ok").inc()
                await out_q.put(DONE)
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 - surfaced to writer
                await out_q.put(("abort", e))

        reader_task = asyncio.ensure_future(reader())
        try:
            while True:
                item = await out_q.get()
                kind = item[0]
                if item is DONE:
                    break
                if kind == "abort":
                    raise item[1]
                if kind == "error":
                    _kind, original, exc = item
                    yield _stream_error_frame(exc.message(), original)
                    continue
                _kind, rid, error_message, response, failure = item
                original = rids.get(rid, "")
                if rid in admitted:
                    admitted.discard(rid)
                    router.release()
                if failure is not None:
                    rids.pop(rid, None)
                    yield _stream_error_frame(failure.message(), original)
                    continue
                spliced, _rid = wire.splice_message_id(response, original)
                out = bytearray()
                wire.encode_stream_response(out, spliced, error_message)
                yield bytes(out)
        finally:
            reader_task.cancel()
            link = state["link"]
            if link is not None:
                for rid in rids:
                    link.unregister(rid)
            for _rid in admitted:
                router.release()
            if state["ep"] is not None:
                router.pool.unpin_stream(state["ep"])

    # -- local health --------------------------------------------------------

    def _fleet_ready(self) -> bool:
        if self.draining:
            return False
        router = self.router
        now = router.now()
        return any(ep.state(now) == "up" for ep in router.pool.endpoints)

    async def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=True)

    async def ServerReady(self, request, context):
        return pb.ServerReadyResponse(ready=self._fleet_ready())

    async def ModelReady(self, request, context):
        if self.router.table.urls_for(request.name):
            return pb.ModelReadyResponse(ready=True)
        # table does not know the model (cold start): ask a backend
        return await self._proxy("ModelReady", request, context)

    # -- proxied control plane -----------------------------------------------

    async def _proxy(self, method_name, request, context):
        router = self.router
        exclude = None
        max_attempts = max(2, router.pool.size)
        for attempt in range(max_attempts):
            ep = router.pool.pick(exclude=exclude)
            link = router.link_for(ep.url)
            try:
                return await getattr(link.stub, method_name)(
                    request, timeout=self.proxy_timeout_s
                )
            except grpc.RpcError as e:
                exc = rpc_error_to_exception(e)
                token = exc.status()
                if status_is_unavailable(token):
                    router.pool.observe(ep, ok=False, token=token)
                    if (
                        attempt + 1 < max_attempts
                        and router.pool.has_alternative(ep)
                    ):
                        exclude = ep
                        continue
                await context.abort(_grpc_code_for(token), exc.message())


def _make_unimplemented(name):
    async def handler(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            f"{name} is not supported by the router tier",
        )

    handler.__name__ = name
    return handler


def _make_proxied(name):
    async def handler(self, request, context):
        return await self._proxy(name, request, context)

    handler.__name__ = name
    return handler


for _name in _PROXIED_METHODS:
    setattr(_RouterServicer, _name, _make_proxied(_name))
for _name in _METHODS:
    if _name not in _RouterServicer.__dict__:
        # shared-memory RPCs and the like: host-local concepts that are
        # meaningless across a proxy hop
        setattr(_RouterServicer, _name, _make_unimplemented(_name))


async def serve_router_grpc(
    router: RouterCore, host: str, port: int
) -> Tuple[object, int, _RouterServicer]:
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
            ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
        ]
    )
    servicer = _RouterServicer(router)
    add_GRPCInferenceServiceServicer_to_server(servicer, server)
    bound = server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server, bound, servicer


# -- HTTP front ---------------------------------------------------------------


class _HttpFront:
    """aiohttp reverse proxy: local health/metrics/status, everything
    else forwarded byte-for-byte to a healthy backend's HTTP address.

    The HTTP infer path cannot see the gRPC priority parameter without
    parsing the JSON body, so HTTP admission uses the DEFAULT priority
    class — latency-protected traffic belongs on gRPC.
    """

    def __init__(self, servicer: _RouterServicer):
        from aiohttp import web

        self.web = web
        self.servicer = servicer
        self.router = servicer.router
        self._session = None
        self.app = web.Application(client_max_size=1 << 30)
        self.app.router.add_get("/v2/health/live", self.handle_live)
        self.app.router.add_get("/v2/health/ready", self.handle_ready)
        self.app.router.add_get("/metrics", self.handle_metrics)
        self.app.router.add_get("/v2/router/status", self.handle_status)
        self.app.router.add_route("*", "/{tail:.*}", self.handle_proxy)

    async def handle_live(self, request):
        return self.web.Response(status=200)

    async def handle_ready(self, request):
        if self.servicer._fleet_ready():
            return self.web.Response(status=200)
        return self.web.Response(
            status=503,
            headers={"Retry-After": "1"},
            text="no healthy backend",
        )

    async def handle_metrics(self, request):
        return self.web.Response(
            text=self.router.metrics.render(),
            content_type="text/plain",
        )

    async def handle_status(self, request):
        return self.web.json_response(self.router.snapshot())

    async def handle_proxy(self, request):
        router = self.router
        is_infer = request.method == "POST" and request.path.endswith(
            "/infer"
        )
        if is_infer:
            try:
                router.admit(0)
            except RouterOverloadError as e:
                router.m_requests.labels("http", "shed").inc()
                return self.web.Response(
                    status=429,
                    headers={"Retry-After": f"{e.retry_after_s:g}"},
                    text=json.dumps({"error": e.message()}),
                    content_type="application/json",
                )
        started = router.now()
        outcome = "error"
        try:
            response = await self._forward_http(request)
            outcome = "ok" if response.status < 500 else "error"
            return response
        finally:
            if is_infer:
                router.release()
                router.m_proxy.observe(router.now() - started)
                router.m_requests.labels("http", outcome).inc()

    async def _forward_http(self, request):
        import aiohttp

        router = self.router
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=None)
            )
        body = await request.read()
        headers = {
            k: v
            for k, v in request.headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        allow = {
            url for url, http_url in router.http_urls.items() if http_url
        }
        if not allow:
            return self.web.Response(
                status=503, text="no HTTP-capable backend"
            )
        exclude = None
        max_attempts = max(2, len(allow))
        for attempt in range(max_attempts):
            ep = router.pool.pick(exclude=exclude, allow=allow)
            target = router.http_urls.get(ep.url)
            if target is None:
                break
            url = f"http://{target}{request.path_qs}"
            started = router.pool.begin(ep)
            try:
                async with self._session.request(
                    request.method, url, data=body, headers=headers
                ) as upstream:
                    payload = await upstream.read()
                    ok = upstream.status < 500
                    router.pool.finish(
                        ep,
                        started,
                        ok=ok,
                        token=None if ok else str(upstream.status),
                    )
                    router.pool.observe(
                        ep,
                        ok=ok,
                        token=None if ok else str(upstream.status),
                    )
                    if (
                        upstream.status == 503
                        and attempt + 1 < max_attempts
                        and router.pool.has_alternative(ep)
                    ):
                        exclude = ep
                        continue
                    out_headers = {
                        k: v
                        for k, v in upstream.headers.items()
                        if k.lower() not in _HOP_HEADERS
                    }
                    return self.web.Response(
                        status=upstream.status,
                        headers=out_headers,
                        body=payload,
                    )
            except aiohttp.ClientError:
                router.pool.finish(ep, started, ok=False, token="503")
                router.pool.observe(ep, ok=False, token="503")
                if (
                    attempt + 1 < max_attempts
                    and router.pool.has_alternative(ep)
                ):
                    exclude = ep
                    continue
                return self.web.Response(
                    status=503,
                    headers={"Retry-After": "1"},
                    text="backend unavailable",
                )
        return self.web.Response(status=503, text="backend unavailable")

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


async def serve_router_http(servicer: _RouterServicer, host: str, port: int):
    from aiohttp import web

    front = _HttpFront(servicer)
    runner = web.AppRunner(front.app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner, front


# -- lifecycle ----------------------------------------------------------------


class RouterServer:
    """Both router fronts on a background event loop in a daemon thread
    (the InProcessServer harness shape). ``backends`` maps each
    backend's gRPC address to its HTTP address (or None)."""

    def __init__(
        self,
        backends: Dict[str, Optional[str]],
        host: str = "127.0.0.1",
        http: bool = True,
        http_port: int = 0,
        grpc_port: int = 0,
        routing_policy="least_outstanding",
        hedge=None,
        max_inflight: int = 0,
        shed_retry_after_s: float = 0.25,
        probe_interval_s: float = 0.25,
        logger=None,
    ):
        self._backends = dict(backends)
        self._host = host
        self._want_http = http
        self._http_bind_port = http_port
        self._grpc_bind_port = grpc_port
        self._routing_policy = routing_policy
        self._hedge = hedge
        self._max_inflight = max_inflight
        self._shed_retry_after_s = shed_retry_after_s
        self._probe_interval_s = probe_interval_s
        self._logger = logger
        self.router: Optional[RouterCore] = None
        self.http_port: Optional[int] = None
        self.grpc_port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._stop = None  # asyncio.Event created on the loop
        self._error: Optional[BaseException] = None
        self._servicer: Optional[_RouterServicer] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RouterServer":
        self._thread = threading.Thread(
            target=self._run, name="client-tpu-router", daemon=True
        )
        self._thread.start()
        self._ready.wait(timeout=60)
        if self._error is not None:
            raise self._error
        if not self._ready.is_set():
            raise RuntimeError("router failed to start in 60s")
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._serve())
        except BaseException as e:  # noqa: BLE001 - propagate to starter
            self._error = e
            self._ready.set()
        finally:
            self._loop.close()

    async def _serve(self) -> None:
        from client_tpu.router.backends import ReadinessProber

        self._stop = asyncio.Event()
        self.router = RouterCore(
            self._backends,
            routing_policy=self._routing_policy,
            hedge=self._hedge,
            max_inflight=self._max_inflight,
            shed_retry_after_s=self._shed_retry_after_s,
            logger=self._logger,
        )
        prober = ReadinessProber(
            self.router, self.router.links, interval_s=self._probe_interval_s
        )
        # resolve the model table before taking traffic; link creation
        # is lazy, so touch every backend's link first
        for url in list(self.router.pool.urls):
            self.router.link_for(url)
        try:
            await prober.probe_once()
        except Exception:  # noqa: BLE001 - backends may still be booting
            pass
        prober.start()
        grpc_server, self.grpc_port, self._servicer = await serve_router_grpc(
            self.router, self._host, self._grpc_bind_port
        )
        http_runner = None
        http_front = None
        if self._want_http:
            http_runner, http_front = await serve_router_http(
                self._servicer, self._host, self._http_bind_port
            )
            self.http_port = http_runner.addresses[0][1]
        self._ready.set()
        await self._stop.wait()
        # flip readiness first so router-pool clients fail over cleanly
        self._servicer.draining = True
        await prober.stop()
        await grpc_server.stop(grace=1)
        if http_runner is not None:
            await http_front.close()
            await http_runner.cleanup()
        await self.router.close()

    def stop(self) -> None:
        if self._loop is not None and self._stop is not None:
            self._loop.call_soon_threadsafe(self._stop.set)
        if self._thread is not None:
            self._thread.join(timeout=30)

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- membership (called from any thread) ---------------------------------

    def add_backend(self, grpc_url: str, http_url: Optional[str] = None):
        """Thread-safe: schedule the join on the router loop (the
        autoscaler calls this from the fleet thread)."""

        def _add():
            self.router.add_backend(grpc_url, http_url)

        asyncio.run_coroutine_threadsafe(
            _call_async(_add), self._loop
        ).result(timeout=10)

    def remove_backend(self, grpc_url: str) -> None:
        """Thread-safe: pull the backend from routing NOW, close its
        link once its in-flights have drained out."""

        async def _remove():
            link = self.router.remove_backend(grpc_url)
            if link is not None:
                # in-flights already forwarded keep their sinks; give
                # them a moment to drain before the channel closes
                for _ in range(50):
                    if link.pending == 0:
                        break
                    await asyncio.sleep(0.1)
                await link.close()

        asyncio.run_coroutine_threadsafe(_remove(), self._loop).result(
            timeout=30
        )

    # -- convenience ---------------------------------------------------------

    @property
    def grpc_url(self) -> str:
        return f"{self._host}:{self.grpc_port}"

    @property
    def http_url(self) -> str:
        return f"{self._host}:{self.http_port}"


async def _call_async(fn):
    return fn()
