"""Router tier: a stateless, protocol-preserving fleet front door.

Clients keep speaking KServe v2 (HTTP or gRPC) to ONE address; the
router classifies each request with the protobuf-free wire scanner,
routes it over live per-backend telemetry (routing policies, outlier
ejection, consistent-hash affinity — the PR-7 client fleet layer run
server-side), splices only the correlation id, and forwards raw bytes
on persistent multiplexed backend streams. Overload sheds
default-priority traffic with ``Retry-After``; the SLO autoscaler
(:mod:`client_tpu.perf.fleet_runner`) grows and drains the replica set
behind it without a client ever noticing.
"""

from client_tpu.router.backends import BackendLink, ReadinessProber  # noqa: F401
from client_tpu.router.core import (  # noqa: F401
    ModelTable,
    RouterCore,
    RouterOverloadError,
)
from client_tpu.router.server import RouterServer  # noqa: F401
