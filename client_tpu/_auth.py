"""Built-in auth plugins.

Reference semantics: src/python/library/tritonclient/_auth.py:33-46.
"""

import base64

from client_tpu._plugin import InferenceServerClientPlugin
from client_tpu._request import Request


class BasicAuth(InferenceServerClientPlugin):
    """HTTP Basic auth plugin: adds an ``Authorization: Basic ...`` header."""

    def __init__(self, username: str, password: str):
        token = base64.b64encode(f"{username}:{password}".encode("utf-8"))
        self._auth_header = f"Basic {token.decode('ascii')}"

    def __call__(self, request: Request) -> None:
        request.headers["Authorization"] = self._auth_header
