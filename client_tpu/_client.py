"""Protocol-agnostic client base: the plugin registry.

Reference semantics: src/python/library/tritonclient/_client.py:31-85 — a
single plugin may be registered per client; every outgoing request's headers
flow through it via ``_call_plugin``.
"""

from typing import Optional

from client_tpu._plugin import InferenceServerClientPlugin
from client_tpu._request import Request


class InferenceServerClientBase:
    """Shared base for all protocol clients (HTTP/gRPC, sync/aio)."""

    def __init__(self):
        self._plugin: Optional[InferenceServerClientPlugin] = None

    def register_plugin(self, plugin: InferenceServerClientPlugin) -> None:
        """Register ``plugin`` to be invoked on every request.

        Raises
        ------
        ValueError
            If a plugin is already registered (only one at a time).
        """
        if not isinstance(plugin, InferenceServerClientPlugin):
            raise ValueError(
                "plugin must be an InferenceServerClientPlugin instance"
            )
        if self._plugin is not None:
            raise ValueError(
                "A plugin is already registered; call unregister_plugin() first"
            )
        self._plugin = plugin

    def plugin(self) -> Optional[InferenceServerClientPlugin]:
        """Return the registered plugin, or None."""
        return self._plugin

    def unregister_plugin(self) -> None:
        """Remove the registered plugin.

        Raises
        ------
        ValueError
            If no plugin is registered.
        """
        if self._plugin is None:
            raise ValueError("No plugin is registered")
        self._plugin = None

    def _call_plugin(self, request: Request) -> None:
        """Run the registered plugin (if any) over an outgoing request."""
        if self._plugin is not None:
            self._plugin(request)
