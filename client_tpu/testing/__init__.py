"""Test/bench harness utilities (in-process server, fixtures)."""

import os

from client_tpu.testing.flake import retry_grpc_poller_flake  # noqa: F401
from client_tpu.testing.inprocess import InProcessServer  # noqa: F401


def hermetic_child_env(base=None, repo_path=None):
    """Environment for hermetic-tier child processes: JAX pinned to the
    host backend even on machines whose sitecustomize force-registers a
    TPU-relay PJRT plugin.

    ``JAX_PLATFORMS=cpu`` alone is not enough there: the injected
    sitecustomize calls ``jax.config.update("jax_platforms", ...)`` at
    interpreter startup, and a config update outranks the env var. Its
    whole body is gated on ``PALLAS_AXON_POOL_IPS``, so dropping that
    variable keeps children on the host backend (and alive when the
    relay is unreachable). Device-tier benches must NOT use this.
    """
    env = dict(os.environ if base is None else base)
    if repo_path:
        env["PYTHONPATH"] = (
            repo_path + os.pathsep + env.get("PYTHONPATH", "")
        )
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env
