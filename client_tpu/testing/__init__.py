"""Test/bench harness utilities (in-process server, fixtures)."""

from client_tpu.testing.inprocess import InProcessServer  # noqa: F401
