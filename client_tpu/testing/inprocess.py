"""Run the KServe v2 server in-process on a background event loop.

The harness used by integration tests and by in-process benchmarking (the
role the reference's triton_c_api in-process backend plays: exercising the
full client/server path without a separate server process,
reference src/c++/perf_analyzer/client_backend/triton_c_api/).
"""

import asyncio
import threading
from typing import Optional

from client_tpu.server.core import ServerCore
from client_tpu.server.model_repository import ModelRepository


class InProcessServer:
    """Starts HTTP and/or gRPC front-ends over one ServerCore in a thread."""

    def __init__(
        self,
        core: Optional[ServerCore] = None,
        http: bool = True,
        grpc=True,
        host: str = "127.0.0.1",
        builtin_models: bool = True,
        chaos=None,
        http_port: int = 0,
        grpc_port: int = 0,
        drain_timeout_s: float = 5.0,
    ):
        """`grpc` may be True (native front-end when built, else grpc.aio),
        "native", "aio", or False.

        ``chaos`` (a :class:`client_tpu.resilience.ChaosPolicy`) injects
        faults — error rate, latency, resets, truncated bodies — into
        both front-ends; with chaos active the gRPC front-end is forced
        to the grpc.aio implementation (the native C++ front-end has no
        injection hooks).

        ``http_port``/``grpc_port`` default to 0 (ephemeral); rolling-
        restart tests pass the previous instance's ports so a restarted
        server comes back at the same address an
        :class:`~client_tpu.lifecycle.EndpointPool` keeps probing.

        ``drain_timeout_s`` bounds the graceful half of :meth:`stop`:
        readiness flips false immediately, in-flight and queued work gets
        this long to finish, and only then do the front-ends close and
        anything left fail — with a clean 503/UNAVAILABLE, never a
        cancelled-future traceback."""
        if core is None:
            core = ServerCore(ModelRepository())
        self.core = core
        self.chaos = chaos
        if builtin_models:
            from client_tpu.server.models import register_builtin_models

            register_builtin_models(self.core.repository)
        self._want_http = http
        if grpc is True:
            if chaos is not None:
                grpc = "aio"
            else:
                from client_tpu.server.native_frontend import native_available

                grpc = "native" if native_available() else "aio"
        elif grpc == "native" and chaos is not None:
            raise ValueError(
                "chaos injection is not supported by the native gRPC "
                "front-end; use grpc='aio'"
            )
        self._want_grpc = grpc
        self.grpc_impl: Optional[str] = grpc if grpc else None
        self._host = host
        self._http_bind_port = http_port
        self._grpc_bind_port = grpc_port
        self._drain_timeout_s = drain_timeout_s
        self.http_port: Optional[int] = None
        self.grpc_port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._stop = None  # asyncio.Event created on the loop
        self._error: Optional[BaseException] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "InProcessServer":
        self._thread = threading.Thread(
            target=self._run, name="client-tpu-server", daemon=True
        )
        self._thread.start()
        self._ready.wait(timeout=60)
        if self._error is not None:
            raise self._error
        if not self._ready.is_set():
            raise RuntimeError("in-process server failed to start in 60s")
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._serve())
        except BaseException as e:  # noqa: BLE001 - propagate to starter
            self._error = e
            self._ready.set()
        finally:
            self._loop.close()

    async def _serve(self) -> None:
        self._stop = asyncio.Event()
        http_runner = None
        grpc_server = None
        native_frontend = None
        if self._want_http:
            from client_tpu.server.http_server import serve_http

            http_runner = await serve_http(
                self.core, self._host, self._http_bind_port, chaos=self.chaos
            )
            self.http_port = http_runner.addresses[0][1]
        if self._want_grpc == "native":
            from client_tpu.server.native_frontend import serve_grpc_native

            native_frontend, self.grpc_port = await serve_grpc_native(
                self.core, self._host, self._grpc_bind_port
            )
        elif self._want_grpc:
            from client_tpu.server.grpc_server import serve_grpc

            grpc_server, self.grpc_port = await serve_grpc(
                self.core, self._host, self._grpc_bind_port, chaos=self.chaos
            )
        self._ready.set()
        await self._stop.wait()
        # Graceful half BEFORE the front-ends close: readiness flips
        # false (new requests 503/UNAVAILABLE) while in-flight AND queued
        # batcher work finishes inside the drain deadline; past it,
        # queued entries fail with the same clean error. Previously the
        # front-ends stopped first and core.close() cancelled in-flight
        # futures into cancelled-asyncio tracebacks.
        try:
            await self.core.drain(self._drain_timeout_s)
        except Exception:  # noqa: BLE001 - shutdown must proceed
            pass
        if native_frontend is not None:
            native_frontend.stop()
        if grpc_server is not None:
            await grpc_server.stop(grace=1)
        if http_runner is not None:
            await http_runner.cleanup()

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        """Drain (bounded by ``drain_timeout_s``, default the value the
        server was built with) and shut down."""
        if drain_timeout_s is not None:
            self._drain_timeout_s = drain_timeout_s
        if self._loop is not None and self._stop is not None:
            self._loop.call_soon_threadsafe(self._stop.set)
        if self._thread is not None:
            self._thread.join(timeout=self._drain_timeout_s + 10)
        self.core.close()

    def __enter__(self) -> "InProcessServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- profiling ----------------------------------------------------------

    def profile(self, duration_s: float = 1.0, hz: float = 99.0):
        """Sample this server's threads for ``duration_s`` seconds and
        return the :class:`~client_tpu.observability.profiling.
        ProfileResult` (collapsed()/speedscope() exporters). The sampler
        runs on the CALLING thread — the server's loop, executor, and
        pump threads keep serving and show up in the samples; the
        caller's own stack is excluded. The in-process twin of
        ``GET /v2/debug/profile``."""
        from client_tpu.observability.profiling import WallProfiler

        return WallProfiler(hz=hz).run(duration_s)

    # -- convenience --------------------------------------------------------

    @property
    def http_url(self) -> str:
        return f"{self._host}:{self.http_port}"

    @property
    def grpc_url(self) -> str:
        return f"{self._host}:{self.grpc_port}"
