"""Retry shim for grpcio's process-global aio poller flake.

Deep into a long test or bench session, grpcio's process-global aio
poller occasionally breaks down with EAGAIN (upstream flake, observed as
a driver run that completes with ZERO successful requests while the
server is demonstrably healthy). The affected call sites — the
genai-perf e2e test and the bench.py LLM cells — all carried their own
copy of the same two-attempt loop; this is the one shared
implementation. A genuine regression fails every attempt, so the retry
cannot mask one.
"""

from typing import Callable, TypeVar

T = TypeVar("T")

# Why two: one retry is enough to ride over a single poller breakdown,
# and every extra attempt doubles how long a REAL regression takes to
# fail. No caller has ever needed a third.
DEFAULT_ATTEMPTS = 2


def retry_grpc_poller_flake(
    run: Callable[[], T],
    succeeded: Callable[[T], bool],
    attempts: int = DEFAULT_ATTEMPTS,
) -> T:
    """Run ``run()`` up to ``attempts`` times until ``succeeded(result)``.

    ``run`` performs one full driver pass (it may raise — exceptions
    propagate immediately, only the zero-requests flake signature is
    retried); ``succeeded`` classifies its result. The LAST result is
    returned either way so callers assert on it and fail with the real
    evidence when every attempt came up empty.
    """
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    result = run()
    for _ in range(attempts - 1):
        if succeeded(result):
            break
        result = run()
    return result
