"""Resource-pool rate limiter (ModelRateLimiter semantics).

Models may declare resource demands (``rate_limiter = {"resources":
[{"name": "accel_slot", "count": 1}], "priority": 1}``); the server core
acquires those resources around every device execution, so models sharing
a pool serialize instead of oversubscribing the device. Pool capacity
defaults to the maximum any model demands (the reference's behavior when
no explicit resource counts are configured server-side) and can be pinned
with :meth:`set_capacity`.

Waiters are granted strictly in (priority, arrival) order — priority 0 is
highest, matching ModelRateLimiter priority semantics — from whichever
thread calls :meth:`release`; asyncio waiters are woken through their own
loop. No wall-clock reads (blocking waits take their timeout from the
caller), so the limiter is fake-clock friendly by construction.
"""

import asyncio
import threading
from typing import Dict, List, Optional


class _Waiter:
    __slots__ = ("resources", "priority", "seq", "granted", "_event", "_loop", "_future")

    def __init__(self, resources, priority, seq, loop=None, future=None):
        self.resources = resources
        self.priority = priority
        self.seq = seq
        self.granted = False
        self._event = threading.Event() if loop is None else None
        self._loop = loop
        self._future = future

    def wake(self) -> None:
        if self._event is not None:
            self._event.set()
        else:
            def _set(future=self._future):
                if not future.done():
                    future.set_result(True)

            self._loop.call_soon_threadsafe(_set)

    def wait_blocking(self, timeout_s: Optional[float]) -> bool:
        return self._event.wait(timeout_s)


class RateLimiter:
    """Named resource pools guarding device executions. Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._capacity: Dict[str, int] = {}
        self._used: Dict[str, int] = {}
        self._waiters: List[_Waiter] = []
        self._seq = 0

    # -- capacity ------------------------------------------------------------

    def register(self, resources: Dict[str, int]) -> None:
        """Grow pool capacities to cover a model's demand (capacity is
        the max demanded by any registered model unless pinned)."""
        with self._lock:
            for name, count in resources.items():
                self._capacity[name] = max(
                    self._capacity.get(name, 0), int(count)
                )

    def set_capacity(self, name: str, count: int) -> None:
        """Pin a pool's capacity explicitly (operator override)."""
        with self._lock:
            self._capacity[name] = int(count)
        self._grant_waiters()

    def available(self, name: str) -> int:
        with self._lock:
            return self._capacity.get(name, 0) - self._used.get(name, 0)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-pool occupancy under one lock: capacity, grants in use,
        and parked waiters (``/v2/debug/state`` building block)."""
        with self._lock:
            waiting: Dict[str, int] = {}
            for waiter in self._waiters:
                for name in waiter.resources:
                    waiting[name] = waiting.get(name, 0) + 1
            return {
                name: {
                    "capacity": capacity,
                    "used": self._used.get(name, 0),
                    "waiters": waiting.get(name, 0),
                }
                for name, capacity in self._capacity.items()
            }

    # -- acquisition ---------------------------------------------------------

    def _fits_locked(self, resources: Dict[str, int]) -> bool:
        for name, count in resources.items():
            if (
                self._used.get(name, 0) + count
                > self._capacity.get(name, 0)
            ):
                return False
        return True

    def _take_locked(self, resources: Dict[str, int]) -> None:
        for name, count in resources.items():
            self._used[name] = self._used.get(name, 0) + count

    def release(self, resources: Dict[str, int]) -> None:
        with self._lock:
            for name, count in resources.items():
                self._used[name] = max(0, self._used.get(name, 0) - count)
        self._grant_waiters()

    def _grant_waiters(self) -> None:
        granted: List[_Waiter] = []
        with self._lock:
            # strict (priority, arrival) order: a waiter that does not
            # fit blocks everyone behind it — no starvation of large
            # demands by a stream of small ones
            self._waiters.sort(key=lambda w: (w.priority, w.seq))
            while self._waiters and self._fits_locked(
                self._waiters[0].resources
            ):
                waiter = self._waiters.pop(0)
                self._take_locked(waiter.resources)
                waiter.granted = True
                granted.append(waiter)
        for waiter in granted:
            waiter.wake()

    def _enqueue(self, resources, priority, loop=None, future=None):
        self._seq += 1
        waiter = _Waiter(resources, priority, self._seq, loop, future)
        self._waiters.append(waiter)
        return waiter

    def _abandon(self, waiter: _Waiter) -> bool:
        """Back out of a wait; returns True when the waiter had already
        been granted (the caller then owns — and must release — the
        resources)."""
        with self._lock:
            if waiter.granted:
                return True
            try:
                self._waiters.remove(waiter)
            except ValueError:
                pass
            return False

    async def acquire(
        self, resources: Dict[str, int], priority: int = 0
    ) -> None:
        """Await the resources (asyncio path; the event-loop batcher)."""
        loop = asyncio.get_running_loop()
        with self._lock:
            if not self._waiters and self._fits_locked(resources):
                self._take_locked(resources)
                return
            future = loop.create_future()
            waiter = self._enqueue(resources, priority, loop, future)
        try:
            await future
        except asyncio.CancelledError:
            if self._abandon(waiter):
                self.release(resources)
            raise

    def acquire_blocking(
        self,
        resources: Dict[str, int],
        priority: int = 0,
        timeout_s: Optional[float] = None,
    ) -> bool:
        """Blocking twin for the synchronous direct path; returns False
        when ``timeout_s`` elapses without a grant."""
        with self._lock:
            if not self._waiters and self._fits_locked(resources):
                self._take_locked(resources)
                return True
            waiter = self._enqueue(resources, priority)
        if waiter.wait_blocking(timeout_s):
            return True
        if self._abandon(waiter):
            # the grant raced the timeout: we own the resources after all
            return True
        return False
