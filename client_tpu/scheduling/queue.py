"""Multi-level priority queue with deadline expiry.

Backs ``_ModelBatcher.pending``: entries live on one of ``levels`` FIFO
deques (level 1 = highest priority) and are consumed in (level, arrival)
order, so scheduling is a stable priority sort. A timed-out entry is
either removed and returned by :meth:`expire` (``timeout_action
"reject"``) or demoted to a trailing lane served only when every live
level is empty (``"continue"`` — Triton's DELAY semantics).

The batcher's take path needs an ordered scan with selective removal
(batch-compatibility may skip entries), so the consuming API is
:meth:`scan` + :meth:`remove` rather than a pop: scan cost is O(queued)
per batch, bounded by ``max_queue_size`` (see PERF.md on the priority-pop
cost). No wall-clock reads — ``expire`` takes "now" from the caller.
"""

from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from client_tpu.scheduling.policy import (
    TIMEOUT_ACTION_CONTINUE,
    TIMEOUT_ACTION_REJECT,
)


class QueueItem:
    """One queued entry (the queue owns the wrapper, callers the value)."""

    __slots__ = ("value", "level", "seq", "deadline_ns", "timeout_action", "demoted")

    def __init__(self, value, level, seq, deadline_ns, timeout_action):
        self.value = value
        self.level = level
        self.seq = seq
        self.deadline_ns = deadline_ns
        self.timeout_action = timeout_action
        self.demoted = False


class PriorityQueue:
    """Stable multi-level FIFO; NOT thread-safe (single-loop batcher use)."""

    def __init__(self, levels: int = 1):
        self._levels: List[deque] = [deque() for _ in range(max(1, levels))]
        self._delayed: deque = deque()  # timed-out "continue" entries
        self._seq = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def push(
        self,
        value: Any,
        level: int = 1,
        deadline_ns: Optional[int] = None,
        timeout_action: str = TIMEOUT_ACTION_REJECT,
    ) -> QueueItem:
        """Enqueue at ``level`` (clamped to the configured range)."""
        index = min(max(1, level), len(self._levels)) - 1
        self._seq += 1
        item = QueueItem(
            value, index + 1, self._seq, deadline_ns, timeout_action
        )
        self._levels[index].append(item)
        self._size += 1
        return item

    def scan(self) -> List[QueueItem]:
        """All queued items in consumption order: level 1..N FIFO, then
        the demoted (timed-out "continue") lane."""
        out: List[QueueItem] = []
        for lane in self._levels:
            out.extend(lane)
        out.extend(self._delayed)
        return out

    def remove(self, items: Iterable[QueueItem]) -> None:
        """Remove specific items (identity comparison)."""
        drop = set(map(id, items))
        if not drop:
            return
        for i, lane in enumerate(self._levels):
            if any(id(item) in drop for item in lane):
                self._levels[i] = deque(
                    item for item in lane if id(item) not in drop
                )
        if any(id(item) in drop for item in self._delayed):
            self._delayed = deque(
                item for item in self._delayed if id(item) not in drop
            )
        self._size = sum(map(len, self._levels)) + len(self._delayed)

    def expire(self, now_ns: int) -> List[QueueItem]:
        """Apply deadline expiry as of ``now_ns``.

        Returns the items whose action is ``"reject"`` (removed from the
        queue; the caller fails their requests). ``"continue"`` items are
        demoted in place to the trailing lane and not returned; their
        deadline is cleared so they expire only once.
        """
        rejected: List[QueueItem] = []
        for i, lane in enumerate(self._levels):
            expired = [
                item
                for item in lane
                if item.deadline_ns is not None and now_ns > item.deadline_ns
            ]
            if not expired:
                continue
            keep = deque(
                item
                for item in lane
                if item.deadline_ns is None or now_ns <= item.deadline_ns
            )
            self._levels[i] = keep
            for item in expired:
                if item.timeout_action == TIMEOUT_ACTION_CONTINUE:
                    item.demoted = True
                    item.deadline_ns = None
                    self._delayed.append(item)
                else:
                    rejected.append(item)
        if rejected:
            self._size -= len(rejected)
        return rejected

    def depths(self) -> Dict[int, int]:
        """Queued entries per level (demoted entries count under their
        original level)."""
        depths = {i + 1: len(lane) for i, lane in enumerate(self._levels)}
        for item in self._delayed:
            depths[item.level] = depths.get(item.level, 0) + 1
        return depths
