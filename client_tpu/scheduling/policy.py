"""Queue policies, admission errors, and the non-queue admission gate.

The configuration surface mirrors the reference proto
(model_config.proto): ``ModelQueuePolicy`` (max_queue_size,
default_timeout_microseconds, timeout_action REJECT/DELAY,
allow_timeout_override) and the priority half of ``ModelDynamicBatching``
(priority_levels, default_priority_level), plus the resource demands of
``ModelRateLimiter``. A model declares them as plain attributes
(:class:`client_tpu.server.model_repository.Model`); the server core
resolves one :class:`QueuePolicy` per model and stamps every admitted
request with its effective priority level and queue deadline.

No wall-clock reads here: callers pass ``arrival_ns``/"now" values in
(clock-injection lint enforced).
"""

import threading
from typing import Any, Dict, Optional

from client_tpu.utils import InferenceServerException

# Request parameters that carry scheduling intent ("priority" is the
# ModelInferRequest uint64 priority, "timeout"/"timeout_us" the queue
# timeout in microseconds). They are consumed by the admission layer and
# MUST be excluded from batch-compatibility signatures: two same-shape
# requests that differ only in scheduling params still share a device
# execution.
SCHEDULING_PARAM_KEYS = frozenset({"priority", "timeout", "timeout_us"})

# What happens to a request whose queue deadline passes before execution:
# "reject" fails it with a deadline error (Triton TIMEOUT_ACTION REJECT);
# "continue" demotes it behind every in-deadline request and executes it
# when nothing else is waiting (Triton TIMEOUT_ACTION DELAY).
TIMEOUT_ACTION_REJECT = "reject"
TIMEOUT_ACTION_CONTINUE = "continue"
_TIMEOUT_ACTIONS = (TIMEOUT_ACTION_REJECT, TIMEOUT_ACTION_CONTINUE)


class SchedulingError(InferenceServerException):
    """Base class for admission-control rejections.

    Carries both wire faces so each front-end can map it without parsing
    messages: ``http_status`` (+ optional ``retry_after_s`` rendered as a
    ``Retry-After`` header) and ``grpc_code`` (a grpc.StatusCode name).
    The exception ``status()`` is the gRPC code name, which the client
    resilience layer already classifies as retryable.
    """

    http_status = 503
    grpc_code = "UNAVAILABLE"
    # label value for tpu_queue_rejected_total{reason=...}
    reason = "scheduling"

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg, status=self.grpc_code)
        self.retry_after_s = retry_after_s


class QueueFullError(SchedulingError):
    """The model's scheduler queue is at ``max_queue_size``."""

    http_status = 429
    grpc_code = "RESOURCE_EXHAUSTED"
    reason = "queue_full"

    def __init__(
        self,
        model_name: str,
        max_queue_size: int,
        retry_after_s: float = 1.0,
    ):
        super().__init__(
            f"inference queue for model '{model_name}' is full "
            f"(max_queue_size {max_queue_size}); request rejected",
            retry_after_s=retry_after_s,
        )


class QueueTimeoutError(SchedulingError):
    """A request's queue deadline passed before it reached the device."""

    http_status = 504
    grpc_code = "DEADLINE_EXCEEDED"
    reason = "timeout"

    def __init__(self, model_name: str, timeout_us: int):
        super().__init__(
            f"request to model '{model_name}' timed out in queue "
            f"(queue timeout {timeout_us} us exceeded before execution)"
        )


class QueuePolicy:
    """Per-model admission configuration, resolved once per model load.

    ``priority_levels`` N declares levels ``1..N`` (1 = highest, matching
    Triton). Requests that carry no ``priority`` parameter land on
    ``default_priority_level`` when set, else on the LOWEST level —
    unprioritized traffic never jumps ahead of traffic that asked.
    ``max_queue_size`` 0 disables the bound; ``default_timeout_us`` 0
    disables the default deadline.
    """

    __slots__ = (
        "model",
        "max_queue_size",
        "default_timeout_us",
        "timeout_action",
        "allow_timeout_override",
        "priority_levels",
        "default_priority_level",
        "rate_resources",
        "rate_priority",
    )

    def __init__(
        self,
        model=None,
        max_queue_size: int = 0,
        default_timeout_us: int = 0,
        timeout_action: str = TIMEOUT_ACTION_REJECT,
        allow_timeout_override: bool = True,
        priority_levels: int = 0,
        default_priority_level: int = 0,
        rate_resources: Optional[Dict[str, int]] = None,
        rate_priority: int = 0,
    ):
        if timeout_action not in _TIMEOUT_ACTIONS:
            raise ValueError(
                f"timeout_action must be one of {_TIMEOUT_ACTIONS}, got "
                f"{timeout_action!r}"
            )
        self.model = model
        self.max_queue_size = max(0, int(max_queue_size))
        self.default_timeout_us = max(0, int(default_timeout_us))
        self.timeout_action = timeout_action
        self.allow_timeout_override = bool(allow_timeout_override)
        self.priority_levels = max(0, int(priority_levels))
        self.default_priority_level = max(0, int(default_priority_level))
        self.rate_resources = dict(rate_resources or {})
        self.rate_priority = int(rate_priority)

    @classmethod
    def from_model(cls, model) -> "QueuePolicy":
        """Resolve a model's scheduling declarations (all optional)."""
        declared = getattr(model, "queue_policy", None) or {}
        limiter = getattr(model, "rate_limiter", None) or {}
        resources = {
            str(r["name"]): int(r.get("count", 1))
            for r in limiter.get("resources", [])
        }
        return cls(
            model=model,
            max_queue_size=declared.get("max_queue_size", 0),
            default_timeout_us=declared.get("default_timeout_us", 0),
            timeout_action=declared.get(
                "timeout_action", TIMEOUT_ACTION_REJECT
            ),
            allow_timeout_override=declared.get(
                "allow_timeout_override", True
            ),
            priority_levels=getattr(model, "priority_levels", 0) or 0,
            default_priority_level=getattr(
                model, "default_priority_level", 0
            )
            or 0,
            rate_resources=resources,
            rate_priority=limiter.get("priority", 0),
        )

    @property
    def levels(self) -> int:
        """Number of queue levels actually maintained (>= 1)."""
        return max(1, self.priority_levels)

    def priority_of(self, parameters: Dict[str, Any]) -> int:
        """Effective queue level for a request's parameters (1 = highest).

        Out-of-range values clamp to the nearest level; missing/zero
        falls to ``default_priority_level``, else the lowest level.
        """
        levels = self.levels
        try:
            priority = int(parameters.get("priority", 0) or 0)
        except (TypeError, ValueError):
            priority = 0
        if priority <= 0:
            priority = self.default_priority_level or levels
        return min(max(1, priority), levels)

    def timeout_us_of(self, parameters: Dict[str, Any]) -> int:
        """Effective queue timeout in microseconds (0 = none)."""
        timeout_us = 0
        if self.allow_timeout_override:
            raw = parameters.get("timeout", parameters.get("timeout_us", 0))
            try:
                timeout_us = int(raw or 0)
            except (TypeError, ValueError):
                timeout_us = 0
        if timeout_us <= 0:
            timeout_us = self.default_timeout_us
        return max(0, timeout_us)

    def deadline_ns(
        self, parameters: Dict[str, Any], arrival_ns: int
    ) -> Optional[int]:
        timeout_us = self.timeout_us_of(parameters)
        if not timeout_us:
            return None
        return arrival_ns + timeout_us * 1000

    def stamp(self, request, arrival_ns: int) -> None:
        """Resolve and attach the request's scheduling fields
        (``priority_level``, ``deadline_ns``) once, at admission."""
        request.priority_level = self.priority_of(request.parameters)
        request.deadline_ns = self.deadline_ns(request.parameters, arrival_ns)

    @property
    def enabled(self) -> bool:
        """True when the MODEL configures admission behavior. A request
        may still opt in via its own ``timeout`` parameter on an
        unconfigured model (``allow_timeout_override`` defaults True), so
        ``ServerCore._admit_single`` skips stamping and the gate only
        when the policy is disabled AND the request carries no
        parameters at all."""
        return bool(
            self.max_queue_size
            or self.default_timeout_us
            or self.priority_levels
            or self.rate_resources
        )


class _Ticket:
    """One admitted request's handle on an :class:`AdmissionGate`.

    ``started()`` moves the request out of the waiting room (idempotent,
    thread-safe: the executor thread marks it when execution begins and
    the owning coroutine's ``finally`` closes it as a safety net — a
    request cancelled before its executor slot ran must not leak the
    waiting count)."""

    __slots__ = ("_gate", "_open")

    def __init__(self, gate: "AdmissionGate"):
        self._gate = gate
        self._open = True

    def started(self) -> None:
        gate = self._gate
        with gate._lock:
            if self._open:
                self._open = False
                gate.waiting -= 1

    close = started  # alias: `finally: ticket.close()` reads better


class AdmissionGate:
    """Waiting-room bound for execution paths without an explicit queue.

    The single, direct, and decoupled paths have no scheduler queue — a
    request "queues" in the thread-pool executor (or the pump thread's
    batch grouping). This gate bounds how many admitted requests may be
    waiting to start executing: ``enter()`` rejects with
    :class:`QueueFullError` once ``max_queue_size`` requests are waiting,
    and returns a ticket whose ``started()`` releases the slot when
    execution begins. Requests actively executing never count against
    the bound (matching the batcher, whose in-flight batch is outside
    its queue)."""

    __slots__ = ("policy", "_lock", "waiting")

    def __init__(self, policy: QueuePolicy):
        self.policy = policy
        self._lock = threading.Lock()
        self.waiting = 0

    def enter(self, model_name: str) -> _Ticket:
        max_size = self.policy.max_queue_size
        with self._lock:
            if max_size and self.waiting >= max_size:
                raise QueueFullError(model_name, max_size)
            self.waiting += 1
        return _Ticket(self)
