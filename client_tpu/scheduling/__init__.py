"""Scheduling & admission control: queue policies, priority queues, and
overload shedding.

The server-side QoS layer between the front-ends and the execution engine
(the role Triton's dynamic-batch scheduler queue policies and rate limiter
play; reference model_config.proto ModelQueuePolicy / ModelDynamicBatching
priority_levels / ModelRateLimiter):

- :class:`QueuePolicy` — per-model admission configuration
  (``max_queue_size``, ``default_timeout_us``, ``timeout_action``,
  ``allow_timeout_override``, ``priority_levels``,
  ``default_priority_level``) resolved from the model's declarations.
- :class:`PriorityQueue` — bounded multi-level FIFO (lower level number =
  higher priority; stable arrival order within a level) with deadline
  expiry. Backs ``_ModelBatcher.pending``.
- :class:`RateLimiter` — grants device executions against named resource
  pools, waking waiters in priority order (ModelRateLimiter semantics).
- :class:`AdmissionGate` — waiting-room counter for the execution paths
  that have no explicit queue (single / direct / decoupled).

Everything here is clock-injectable: no function in this package reads a
wall clock itself — "now" values are passed in by the caller (the server
core) or produced by an injected ``clock_ns`` — so the whole subsystem is
tested with fake clocks in milliseconds of wall time (enforced by
``tools/clock_lint.py``).
"""

from client_tpu.scheduling.policy import (
    SCHEDULING_PARAM_KEYS,
    TIMEOUT_ACTION_CONTINUE,
    TIMEOUT_ACTION_REJECT,
    AdmissionGate,
    QueueFullError,
    QueuePolicy,
    QueueTimeoutError,
    SchedulingError,
)
from client_tpu.scheduling.queue import PriorityQueue
from client_tpu.scheduling.rate_limiter import RateLimiter

__all__ = [
    "SCHEDULING_PARAM_KEYS",
    "TIMEOUT_ACTION_CONTINUE",
    "TIMEOUT_ACTION_REJECT",
    "AdmissionGate",
    "PriorityQueue",
    "QueueFullError",
    "QueuePolicy",
    "QueueTimeoutError",
    "RateLimiter",
    "SchedulingError",
]
