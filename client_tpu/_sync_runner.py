"""A background event loop for exposing the asyncio clients synchronously.

The reference built its sync HTTP client on gevent greenlets and later added
separate aio implementations; here the asyncio implementation is primary and
sync surfaces delegate to it through one dedicated loop thread per client.
"""

import asyncio
import atexit
import concurrent.futures
import threading
from typing import Any, Coroutine, Optional


class EventLoopRunner:
    """Owns a daemon thread running an asyncio event loop."""

    def __init__(self, name: str = "client-tpu-loop"):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()
        atexit.register(self.close)

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.call_soon(self._started.set)
        self._loop.run_forever()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop

    def submit(self, coro: Coroutine) -> concurrent.futures.Future:
        """Schedule ``coro`` on the loop; returns a concurrent Future."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def run(self, coro: Coroutine, timeout: Optional[float] = None) -> Any:
        """Run ``coro`` to completion and return its result (blocking)."""
        if threading.current_thread() is self._thread:
            # Blocking on our own loop would deadlock (e.g. GC finalizers
            # running on the loop thread); fail fast instead.
            coro.close()
            raise RuntimeError(
                "EventLoopRunner.run called from its own loop thread"
            )
        return self.submit(coro).result(timeout)

    def close(self) -> None:
        if self._loop.is_closed():
            return
        if self._thread.is_alive():
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)
        if not self._loop.is_closed():
            self._loop.close()
        try:
            atexit.unregister(self.close)
        except Exception:
            pass
