"""Step-dispatch bus: how the pod coordinator keeps workers in lockstep.

Multi-process SPMD has one iron rule: every process must enter every
collective-bearing computation, in the same order, with the same shapes.
The serving front-ends run only on process 0, so the coordinator owns
the request stream and BROADCASTS each device-call descriptor (op name +
host-side args) to the workers before launching its own copy; each
worker's follower loop executes the same call against its local shard
state. The physical KV pool and the parameters never ride the bus —
each process holds its own (identically initialized) shards; only the
small per-step host arrays (token ids, positions, page tables) travel.

The wire is a plain length-prefixed TCP frame (JSON header + raw array
bytes) between processes the launcher spawned on one host — the
jax.distributed coordination service underneath is already gRPC, and
the serving traffic into the pod is gRPC; this bus is the thin dispatch
lane between them.

Failure semantics (the reason acks exist): workers ack RECEIPT of every
descriptor before executing it. The coordinator requires all acks —
with a bounded timeout — before entering the computation itself, so a
dead worker surfaces as :class:`PodWorkerLostError` (a retryable
UNAVAILABLE) at the broadcast, never as a gloo collective hanging on a
peer that will never arrive. Acks carry the worker's cumulative
device-busy nanoseconds, which is where the per-process duty split in
the bench/fleet report comes from.
"""

import json
import socket
import struct
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from client_tpu.utils import InferenceServerException

#: sentinel op the coordinator broadcasts at shutdown
STOP_OP = "__stop__"
#: sentinel op the coordinator broadcasts when the pod is re-assembling
#: after a member loss: args carry (new_coordinator_address, epoch);
#: surviving workers leave the follower loop, re-join jax.distributed at
#: the new address, and reconnect to a fresh bus
REINIT_OP = "__reinit__"

_LEN = struct.Struct(">I")


class PodWorkerLostError(InferenceServerException):
    """A pod worker died or stopped acking: the pod cannot run its next
    SPMD step. Retryable UNAVAILABLE — the fleet's retry/failover
    machinery treats it like any dead replica.

    ``reason`` separates the two ways a worker goes missing —
    ``"worker_lost"`` (socket dead: the process exited) and
    ``"ack_timeout"`` (socket alive but silent past the ack deadline: a
    hung process). The supervisor treats both identically (respawn), but
    operators debugging a wedge need to know which one fired."""

    def __init__(self, msg: str, reason: str = "worker_lost"):
        super().__init__(msg, status="UNAVAILABLE")
        self.reason = reason


# ---------------------------------------------------------------------------
# framing: [4-byte len][json header][raw array bytes...]


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("bus peer closed the connection")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, length)


def encode_step(op: str, args: Tuple[Any, ...]) -> bytes:
    """One step descriptor: op name + host args (numpy arrays and
    scalars). Arrays travel as raw bytes after the JSON header."""
    descriptors: List[Dict[str, Any]] = []
    buffers: List[bytes] = []
    for arg in args:
        if arg is None:
            descriptors.append({"kind": "none"})
        elif isinstance(arg, (bool, np.bool_)):
            descriptors.append({"kind": "bool", "value": bool(arg)})
        elif isinstance(arg, (int, np.integer)):
            descriptors.append({"kind": "int", "value": int(arg)})
        elif isinstance(arg, (float, np.floating)):
            descriptors.append({"kind": "float", "value": float(arg)})
        elif isinstance(arg, str):
            descriptors.append({"kind": "str", "value": arg})
        else:
            array = np.ascontiguousarray(arg)
            raw = array.tobytes()
            descriptors.append(
                {
                    "kind": "array",
                    "dtype": array.dtype.str,
                    "shape": list(array.shape),
                    "nbytes": len(raw),
                }
            )
            buffers.append(raw)
    header = json.dumps({"op": op, "args": descriptors}).encode("utf-8")
    return _LEN.pack(len(header)) + header + b"".join(buffers)


def decode_step(payload: bytes) -> Tuple[str, Tuple[Any, ...]]:
    (header_len,) = _LEN.unpack(payload[: _LEN.size])
    offset = _LEN.size + header_len
    header = json.loads(payload[_LEN.size:offset].decode("utf-8"))
    args: List[Any] = []
    for descriptor in header["args"]:
        kind = descriptor["kind"]
        if kind == "none":
            args.append(None)
        elif kind in ("int", "float", "bool", "str"):
            args.append(descriptor["value"])
        else:
            nbytes = descriptor["nbytes"]
            array = np.frombuffer(
                payload, dtype=np.dtype(descriptor["dtype"]),
                count=int(np.prod(descriptor["shape"], dtype=np.int64)),
                offset=offset,
            ).reshape(descriptor["shape"])
            offset += nbytes
            args.append(array)
    return header["op"], tuple(args)


# ---------------------------------------------------------------------------
# coordinator side


class StepBus:
    """Coordinator half: accept one connection per worker, broadcast
    step descriptors, and require receipt acks before each SPMD launch.

    ``clock`` is injectable per the repo's clock-lint rules; socket
    deadlines use fixed ``settimeout`` values derived from it only for
    accounting, never for control flow the tests cannot fake.
    """

    def __init__(
        self,
        num_workers: int,
        address: Optional[str] = None,
        ack_timeout_s: float = 20.0,
        accept_timeout_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.num_workers = num_workers
        self.ack_timeout_s = ack_timeout_s
        self.accept_timeout_s = accept_timeout_s
        self._clock = clock
        host, port = "127.0.0.1", 0
        if address:
            host, _, port_s = address.rpartition(":")
            port = int(port_s)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(num_workers)
        self._workers: Dict[int, socket.socket] = {}
        self._busy_ns: Dict[int, int] = {}
        self.steps = 0

    @property
    def address(self) -> str:
        host, port = self._listener.getsockname()
        return f"{host}:{port}"

    def accept_workers(self) -> None:
        """Block until every worker has connected and said hello (its
        process index). Bounded by ``accept_timeout_s`` per worker."""
        self._listener.settimeout(self.accept_timeout_s)
        while len(self._workers) < self.num_workers:
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                raise PodWorkerLostError(
                    f"pod bus: only {len(self._workers)}/{self.num_workers} "
                    f"workers connected within {self.accept_timeout_s}s"
                ) from None
            conn.settimeout(self.ack_timeout_s)
            hello = json.loads(_recv_frame(conn).decode("utf-8"))
            index = int(hello["process_index"])
            self._workers[index] = conn
            self._busy_ns[index] = 0

    def broadcast(self, op: str, args: Tuple[Any, ...] = ()) -> None:
        """Send one step descriptor to every worker and collect receipt
        acks. Raises :class:`PodWorkerLostError` — BEFORE the caller
        enters the collective — when any worker is gone."""
        payload = encode_step(op, args)
        for index, conn in list(self._workers.items()):
            try:
                _send_frame(conn, payload)
            except OSError as e:
                self._drop(index)
                raise PodWorkerLostError(
                    f"pod worker {index} unreachable at step broadcast: {e}"
                ) from e
        for index, conn in list(self._workers.items()):
            try:
                ack = json.loads(_recv_frame(conn).decode("utf-8"))
            except socket.timeout:
                # the ack deadline: a HUNG worker (socket open, nothing
                # arriving) must be indistinguishable from a killed one —
                # without this bound the step loop stalls forever on a
                # wedged peer (socket.timeout is an OSError, so catch it
                # first to keep its distinct reason)
                self._drop(index)
                raise PodWorkerLostError(
                    f"pod worker {index} did not ack step '{op}' within "
                    f"{self.ack_timeout_s}s",
                    reason="ack_timeout",
                ) from None
            except (OSError, ValueError, ConnectionError) as e:
                self._drop(index)
                raise PodWorkerLostError(
                    f"pod worker {index} did not ack step '{op}': {e}"
                ) from e
            self._busy_ns[index] = int(ack.get("busy_ns", 0))
        self.steps += 1

    def broadcast_surviving(
        self, op: str, args: Tuple[Any, ...] = ()
    ) -> List[int]:
        """Best-effort broadcast: deliver to every worker still
        connected, silently dropping the ones that fail instead of
        raising. Returns the indices that acked. The recovery path uses
        this for ``__reinit__`` — the dead member must not keep the
        survivors from learning where the pod re-assembles."""
        payload = encode_step(op, args)
        for index, conn in list(self._workers.items()):
            try:
                _send_frame(conn, payload)
            except OSError:
                self._drop(index)
        acked: List[int] = []
        for index, conn in list(self._workers.items()):
            try:
                ack = json.loads(_recv_frame(conn).decode("utf-8"))
            except (OSError, ValueError, ConnectionError):
                self._drop(index)
                continue
            self._busy_ns[index] = int(ack.get("busy_ns", 0))
            acked.append(index)
        return sorted(acked)

    def _drop(self, index: int) -> None:
        """Forget a dead worker (its socket closed) so
        :meth:`alive_workers` — and the liveness gauges fed from it —
        reflect the loss immediately."""
        conn = self._workers.pop(index, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def worker_busy_ns(self) -> Dict[int, int]:
        """Cumulative device-busy nanoseconds per worker, as of each
        worker's most recent ack (one step stale by construction)."""
        return dict(self._busy_ns)

    def alive_workers(self) -> List[int]:
        return sorted(self._workers)

    def stop(self) -> None:
        """Best-effort shutdown broadcast, then close every socket."""
        payload = encode_step(STOP_OP, ())
        for conn in self._workers.values():
            try:
                _send_frame(conn, payload)
            except OSError:
                pass
        for conn in self._workers.values():
            try:
                conn.close()
            except OSError:
                pass
        self._workers.clear()
        try:
            self._listener.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# worker side


class StepFollower:
    """Worker half: connect to the coordinator's bus, then execute every
    broadcast step descriptor through the handler table, acking receipt
    (with cumulative busy time) before each execution."""

    def __init__(
        self,
        address: str,
        process_index: int,
        connect_timeout_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.process_index = process_index
        self._clock = clock
        host, _, port_s = address.rpartition(":")
        deadline = clock() + connect_timeout_s
        last_error: Optional[Exception] = None
        self._sock: Optional[socket.socket] = None
        while clock() < deadline:
            try:
                self._sock = socket.create_connection(
                    (host, int(port_s)), timeout=5.0
                )
                break
            except OSError as e:
                last_error = e
                time.sleep(0.05)
        if self._sock is None:
            raise ConnectionError(
                f"pod bus: worker {process_index} could not reach "
                f"coordinator at {address}: {last_error}"
            )
        self._sock.settimeout(None)  # steps arrive whenever requests do
        _send_frame(
            self._sock,
            json.dumps({"process_index": process_index}).encode("utf-8"),
        )
        self.busy_ns = 0
        self.steps = 0
        #: (new_coordinator_address, epoch) from the most recent
        #: ``__reinit__`` broadcast — how the worker's outer loop learns
        #: where the re-assembling pod lives
        self.reinit_args: Optional[Tuple[Any, ...]] = None

    def follow(self, handlers: Dict[str, Callable[..., None]]) -> str:
        """Run the follower loop until the coordinator broadcasts
        ``__stop__`` / ``__reinit__`` or closes the connection. Returns
        the reason the loop ended (``"stop"``, ``"reinit"`` — with
        :attr:`reinit_args` holding the new coordinator address and
        epoch — or ``"coordinator_gone"``)."""
        while True:
            try:
                op, args = decode_step(_recv_frame(self._sock))
            except (OSError, ConnectionError):
                return "coordinator_gone"
            ack = json.dumps({"busy_ns": self.busy_ns}).encode("utf-8")
            try:
                _send_frame(self._sock, ack)
            except OSError:
                return "coordinator_gone"
            if op == STOP_OP:
                return "stop"
            if op == REINIT_OP:
                self.reinit_args = args
                return "reinit"
            t0 = self._clock()
            handlers[op](*args)
            self.busy_ns += int((self._clock() - t0) * 1e9)
            self.steps += 1

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
