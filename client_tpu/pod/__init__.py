"""Pod-scale serving: the multi-process mesh runtime (ROADMAP item 1).

Every mesh in the repo used to live inside one process. This package is
the coordinator/worker runtime that spans one ``jax.sharding.Mesh``
across N processes, so a model too large for any single process's
devices serves as ONE replica:

- :mod:`client_tpu.pod.runtime` — ``PodConfig``/``initialize``: the
  ``jax.distributed`` bootstrap (coordinator address + process
  index/count), CPU fake-pod collectives (gloo) included;
- :mod:`client_tpu.pod.launcher` — ``PodLauncher``: spawns the N
  processes and hands each its pod identity via environment, mirroring
  ``fleet_runner``'s subprocess machinery (ports-file handoff, SIGTERM
  drain, SIGKILL chaos);
- :mod:`client_tpu.pod.bus` — ``StepBus``/``StepFollower``: the
  coordinator broadcasts every device-call descriptor to the workers so
  all processes enter each SPMD computation in lockstep; a dead worker
  surfaces as a retryable UNAVAILABLE at the next broadcast, never a
  collective hang;
- :mod:`client_tpu.pod.worker` — the serving entrypoint
  (``python -m client_tpu.pod.worker``): process 0 serves gRPC/HTTP
  front-ends over a tp-sharded :class:`~client_tpu.llm.serving.LlmEngineModel`,
  processes 1..N-1 follow the bus.

The sharding seam itself (process-spanning ``MeshPlan``, tp-sharded
paged KV pool, ``shard_map``-wrapped attention kernels) lives where the
single-process versions already live: ``client_tpu/parallel`` and the
model/serving layers.
"""

from client_tpu.pod.bus import (  # noqa: F401
    PodWorkerLostError,
    StepBus,
    StepFollower,
)
from client_tpu.pod.launcher import PodLauncher  # noqa: F401
from client_tpu.pod.runtime import (  # noqa: F401
    PodConfig,
    PodConfigError,
    PodRuntime,
    initialize,
    pod_info,
    reinitialize,
)
from client_tpu.pod.supervisor import PodSupervisor  # noqa: F401

__all__ = [
    "PodConfig",
    "PodConfigError",
    "PodRuntime",
    "PodLauncher",
    "PodSupervisor",
    "PodWorkerLostError",
    "StepBus",
    "StepFollower",
    "initialize",
    "pod_info",
    "reinitialize",
]
