"""``jax.distributed`` bootstrap for a multi-process pod.

A pod is N processes sharing one global device mesh: process 0 runs the
coordinator service, every process calls :func:`initialize` with the
same coordinator address and its own ``process_index``, and after that
``jax.devices()`` returns the GLOBAL device list (local + every other
process's devices) so process-spanning meshes resolve exactly like
single-process ones.

The identity triple (coordinator address, process index, process count)
travels as environment variables — :class:`PodConfig` parses and emits
them — because the launcher hands them to subprocesses and the pytest
``pod`` fixture re-execs tests under them. On CPU the fake pod uses the
gloo collectives backend (``jax_cpu_collectives_implementation``); real
TPU pods get their collectives from the platform and ignore that knob.

``initialize`` must run BEFORE the first jax backend touch: jax freezes
its device count (and its distributed-ness) at first backend init.

Self-healing (PR 20): the distributed runtime is constructed MANUALLY
(service + client via ``xla_extension``) rather than through
``jax.distributed.initialize``, for one reason — survivability.  The
stock client installs a missed-heartbeat callback that LOG(FATAL)s the
whole process the moment a peer dies, and its destructor runs a
shutdown barrier that can never complete against a dead peer (also
fatal).  Building the pieces ourselves lets us (a) swap in a benign
heartbeat callback so a dead peer is an *event*, not a process abort,
and (b) :func:`abandon` a broken runtime by stashing the old
service/client (their destructors must never run) and wiping the
backend caches, after which :func:`reinitialize` assembles a fresh pod
at a NEW coordinator address across survivors + replacement.  This is
validated for the CPU/gloo fake pod this repo's CI runs; real TPU
re-slicing has platform steps this module does not attempt.
"""

import dataclasses
import os
import sys
from typing import Any, Dict, List, Mapping, Optional, Tuple

#: environment handoff keys (launcher -> worker / fixture -> re-exec)
ENV_COORDINATOR = "CLIENT_TPU_POD_COORDINATOR"
ENV_PROCESS_INDEX = "CLIENT_TPU_POD_PROCESS_INDEX"
ENV_PROCESS_COUNT = "CLIENT_TPU_POD_PROCESS_COUNT"
ENV_LOCAL_DEVICES = "CLIENT_TPU_POD_LOCAL_DEVICES"
ENV_BUS = "CLIENT_TPU_POD_BUS"


class PodConfigError(ValueError):
    """The pod environment/identity handoff is malformed (a launcher
    bug — every field is launcher-emitted, never operator-typed)."""


@dataclasses.dataclass(frozen=True)
class PodConfig:
    """One process's pod identity: who coordinates, which process this
    is, how many there are, and (for the CPU fake pod) how many virtual
    devices each process is capped to."""

    coordinator_address: str
    process_index: int
    process_count: int
    #: per-process virtual-device cap (0 = platform default). The cap is
    #: applied via XLA_FLAGS by the launcher BEFORE the process starts —
    #: it is carried here so ``describe()``-style surfaces can report it.
    local_devices: int = 0
    #: step-bus address (coordinator binds, workers connect); None when
    #: the pod runs without the serving bus (e.g. SPMD lockstep tests)
    bus_address: Optional[str] = None
    #: how long ``jax.distributed.initialize`` may wait for the full
    #: pod to assemble before giving up (a missing worker must become a
    #: clean error, not a forever-hang)
    init_timeout_s: float = 60.0

    def __post_init__(self):
        if not self.coordinator_address or ":" not in self.coordinator_address:
            raise PodConfigError(
                f"pod coordinator address must be host:port, got "
                f"{self.coordinator_address!r}"
            )
        if self.process_count < 1:
            raise PodConfigError(
                f"pod process_count must be >= 1, got {self.process_count}"
            )
        if not 0 <= self.process_index < self.process_count:
            raise PodConfigError(
                f"pod process_index {self.process_index} out of range for "
                f"process_count {self.process_count}"
            )

    @property
    def is_coordinator(self) -> bool:
        return self.process_index == 0

    @staticmethod
    def from_env(
        env: Optional[Mapping[str, str]] = None,
    ) -> Optional["PodConfig"]:
        """Parse the pod identity from the environment; ``None`` when the
        process is not a pod member (no coordinator variable set)."""
        env = os.environ if env is None else env
        address = env.get(ENV_COORDINATOR)
        if not address:
            return None
        try:
            index = int(env.get(ENV_PROCESS_INDEX, ""))
            count = int(env.get(ENV_PROCESS_COUNT, ""))
        except ValueError as e:
            raise PodConfigError(
                f"pod process index/count must be integers: {e}"
            ) from e
        local = int(env.get(ENV_LOCAL_DEVICES, "0") or "0")
        return PodConfig(
            coordinator_address=address,
            process_index=index,
            process_count=count,
            local_devices=local,
            bus_address=env.get(ENV_BUS) or None,
        )

    def env(self) -> Dict[str, str]:
        """The environment block a launcher merges into a pod process
        (the inverse of :meth:`from_env`)."""
        block = {
            ENV_COORDINATOR: self.coordinator_address,
            ENV_PROCESS_INDEX: str(self.process_index),
            ENV_PROCESS_COUNT: str(self.process_count),
            ENV_LOCAL_DEVICES: str(self.local_devices),
        }
        if self.bus_address:
            block[ENV_BUS] = self.bus_address
        return block


@dataclasses.dataclass(frozen=True)
class PodRuntime:
    """The live pod after :func:`initialize`: identity plus the observed
    global/local device split (what ``describe()`` surfaces report)."""

    config: PodConfig
    process_index: int
    process_count: int
    global_device_count: int
    local_device_count: int

    def describe(self) -> Dict[str, Any]:
        return {
            "process_index": self.process_index,
            "process_count": self.process_count,
            "global_device_count": self.global_device_count,
            "local_device_count": self.local_device_count,
            "coordinator": self.config.coordinator_address,
        }


# Abandoned distributed runtimes: (service, client) pairs whose
# destructors must NEVER run — a client destructor runs a shutdown
# barrier, and against a dead peer that barrier LOG(FATAL)s the
# surviving process. Leaking one socket pair per recovery is the price
# of staying alive; recoveries are rare by definition.
_ABANDONED: List[Tuple[Any, Any]] = []


def _heartbeat_logger(process_index: int):
    """The client's missed-heartbeat callback. The stock one aborts the
    process; ours records the event and keeps serving — the supervisor
    (watching the step bus) owns the recovery decision, not the
    coordination-service heartbeat."""

    def on_missed(status) -> None:
        try:
            print(
                f"[pod proc {process_index}] coordination heartbeat "
                f"missed: {status}",
                file=sys.stderr,
                flush=True,
            )
        except Exception:  # noqa: BLE001 - a logger must never raise here
            pass

    return on_missed


def _pod_init(
    address: str,
    process_index: int,
    process_count: int,
    timeout_s: float,
) -> None:
    """Construct the distributed runtime by hand and install it as
    jax's global distributed state (see the module docstring for why
    not ``jax.distributed.initialize``). Process 0 additionally hosts
    the coordination service, bound on every interface at the
    address's port."""
    from jax._src import distributed
    from jax._src.lib import xla_extension

    state = distributed.global_state
    if process_index == 0:
        bind = "[::]:" + address.rsplit(":", 1)[1]
        state.service = xla_extension.get_distributed_runtime_service(
            bind,
            process_count,
            heartbeat_interval=10,
            max_missing_heartbeats=10,
        )
    client = xla_extension.get_distributed_runtime_client(
        address,
        process_index,
        init_timeout=int(timeout_s),
        shutdown_on_destruction=False,
        missed_heartbeat_callback=_heartbeat_logger(process_index),
        use_compression=True,
    )
    client.connect()
    state.client = client
    state.process_id = process_index
    state.num_processes = process_count
    state.coordinator_address = address


def initialize(config: PodConfig, platform: Optional[str] = None) -> PodRuntime:
    """Join the pod: bring up ``jax.distributed`` for this process.

    Must run before the first jax backend init (the device count and the
    distributed runtime are frozen there). On the CPU platform the gloo
    collectives backend is selected so cross-process ``psum``/gather
    work on the fake pod; TPU pods take the platform default.

    Raises ``RuntimeError`` (from xla) when the pod cannot assemble
    within ``config.init_timeout_s`` — callers surface that as a load
    failure, not a hang.
    """
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    effective = platform or os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in effective or not effective:
        # the CPU fake pod needs a real collectives implementation; the
        # default ("none") refuses multi-process meshes outright
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    _pod_init(
        config.coordinator_address,
        config.process_index,
        config.process_count,
        config.init_timeout_s,
    )
    return PodRuntime(
        config=config,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        global_device_count=len(jax.devices()),
        local_device_count=len(jax.local_devices()),
    )


def abandon() -> None:
    """Walk away from a broken distributed runtime without dying.

    Stashes the live service/client (so neither destructor — each fatal
    against a dead peer — ever runs), clears jax's compilation caches
    and live backends, and leaves the process ready for
    :func:`reinitialize`. Deliberately NOT ``jax.distributed.shutdown``:
    its barrier hangs-then-aborts when any peer is already dead, which
    is exactly the situation recovery starts from."""
    import jax
    from jax._src import distributed, xla_bridge

    state = distributed.global_state
    if state.service is not None or state.client is not None:
        _ABANDONED.append((state.service, state.client))
    state.service = None
    state.client = None
    jax.clear_caches()
    xla_bridge._clear_backends()


def reinitialize(config: PodConfig, platform: Optional[str] = None) -> PodRuntime:
    """Abandon the current runtime and assemble a fresh pod.

    ``config`` carries the NEW coordinator address (the old port may
    still be held by the abandoned service) and the member's identity in
    the new assembly. Sequencing matters: the coordinator must be inside
    ``reinitialize`` (new service bound) before a replacement process
    calls :func:`initialize` — a client whose RegisterTask times out
    aborts its process rather than raising."""
    abandon()
    return initialize(config, platform=platform)


def pod_info() -> Dict[str, int]:
    """This process's (process_index, process_count) as jax sees them —
    (0, 1) for a plain single-process replica. Safe to call whether or
    not the process ever joined a pod; used by the topology/metadata
    surfaces to stamp every devices block."""
    try:
        import jax

        return {
            "process_index": int(jax.process_index()),
            "process_count": int(jax.process_count()),
        }
    except Exception:  # noqa: BLE001 - no backend available
        return {"process_index": 0, "process_count": 1}
