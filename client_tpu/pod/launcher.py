"""Spawn the pod: N OS processes, one mesh.

``PodLauncher`` mirrors the subprocess machinery ``fleet_runner`` uses
for replica processes — free ports picked by binding port 0, identity
handed to children via environment, readiness published through the
atomic ports-file handoff, SIGTERM drain with a SIGKILL backstop — but
where the fleet spawns N *independent* replicas, the launcher spawns N
processes that assemble into ONE replica: every child gets the same
coordinator address and process count, its own process index, and (for
the CPU fake pod) an ``XLA_FLAGS`` device cap so no single process can
hold the whole mesh. That cap is the point of the CI story: a 2-process
launch serves a model that the per-process device budget makes
unservable by either process alone.

``kill(i)`` (SIGKILL, no warning) exists for the chaos tests: a worker
killed mid-stream must surface at the coordinator as a retryable
UNAVAILABLE via the step bus, never as a hung collective.
"""

import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence

from client_tpu.perf.fleet_runner import read_ports_file
from client_tpu.pod.runtime import PodConfig


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


class PodLauncher:
    """Spawn and supervise the pod's member processes.

    By default each child runs ``python -m client_tpu.pod.worker`` (the
    serving entrypoint); tests substitute their own module/argv to run
    arbitrary lockstep programs under the same identity handoff.
    """

    def __init__(
        self,
        process_count: int = 2,
        devices_per_process: int = 2,
        module: str = "client_tpu.pod.worker",
        extra_args: Sequence[str] = (),
        env_extra: Optional[Dict[str, str]] = None,
        with_bus: bool = True,
        host: str = "127.0.0.1",
        init_timeout_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if process_count < 1:
            raise ValueError(f"process_count must be >= 1, got {process_count}")
        self.process_count = process_count
        self.devices_per_process = devices_per_process
        self.module = module
        self.extra_args = list(extra_args)
        self.env_extra = dict(env_extra or {})
        self.host = host
        self.init_timeout_s = init_timeout_s
        self._clock = clock
        self.coordinator_address = f"{host}:{_free_port(host)}"
        self.bus_address = f"{host}:{_free_port(host)}" if with_bus else None
        self._workdir = tempfile.mkdtemp(prefix="client_tpu_pod_")
        self.ports_file = os.path.join(self._workdir, "pod_ports.json")
        # supervisor -> coordinator recovery-plan handoff (see
        # client_tpu.pod.supervisor.PodSupervisor)
        self.control_file = os.path.join(self._workdir, "pod_control.json")
        self.procs: List[subprocess.Popen] = []
        self._logs: List[str] = []

    def config_for(self, process_index: int) -> PodConfig:
        return PodConfig(
            coordinator_address=self.coordinator_address,
            process_index=process_index,
            process_count=self.process_count,
            local_devices=self.devices_per_process,
            bus_address=self.bus_address,
            init_timeout_s=self.init_timeout_s,
        )

    def _child_env(self, process_index: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.config_for(process_index).env())
        # the fake pod runs on CPU with an artificial per-process device
        # budget — the cap must be in place before the child's first jax
        # backend touch, hence XLA_FLAGS rather than a runtime knob
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count="
            f"{self.devices_per_process}"
        )
        env["CLIENT_TPU_POD_PORTS_FILE"] = self.ports_file
        env["CLIENT_TPU_POD_CONTROL_FILE"] = self.control_file
        # the worker module must import regardless of the parent's cwd
        # (a caller in /tmp launches children that still need this repo
        # on their path)
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        path = env.get("PYTHONPATH", "")
        if root not in path.split(os.pathsep):
            env["PYTHONPATH"] = (
                root + (os.pathsep + path if path else "")
            )
        env.update(self.env_extra)
        return env

    def launch(self) -> "PodLauncher":
        argv = [sys.executable, "-m", self.module, *self.extra_args]
        for index in range(self.process_count):
            log_path = os.path.join(self._workdir, f"pod_proc{index}.log")
            self._logs.append(log_path)
            with open(log_path, "wb") as log:
                proc = subprocess.Popen(
                    argv,
                    env=self._child_env(index),
                    stdout=log,
                    stderr=subprocess.STDOUT,
                    cwd=os.getcwd(),
                )
            self.procs.append(proc)
        return self

    def wait_ready(self, timeout_s: float = 180.0) -> dict:
        """Poll the ports file written by process 0 once its servers are
        up; raises with the tail of every process log when the pod dies
        or stalls instead."""
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            ports = read_ports_file(self.ports_file)
            if ports is not None:
                return ports
            for index, proc in enumerate(self.procs):
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"pod process {index} exited rc={proc.returncode} "
                        f"before the pod came up\n{self.log_tail()}"
                    )
            time.sleep(0.1)
        raise TimeoutError(
            f"pod not ready within {timeout_s}s\n{self.log_tail()}"
        )

    def poll(self) -> List[Optional[int]]:
        return [proc.poll() for proc in self.procs]

    def respawn(self, process_index: int) -> None:
        """Replace one DEAD member with a fresh process under the same
        identity, using the launcher's CURRENT ``coordinator_address``
        (the supervisor moves it to the re-assembled pod's address
        before respawning). The replacement appends to the member's log
        file so chaos evidence keeps both lives."""
        old = self.procs[process_index]
        if old.poll() is None:
            raise RuntimeError(
                f"pod process {process_index} is still running; "
                f"respawn only replaces dead members"
            )
        argv = [sys.executable, "-m", self.module, *self.extra_args]
        with open(self._logs[process_index], "ab") as log:
            proc = subprocess.Popen(
                argv,
                env=self._child_env(process_index),
                stdout=log,
                stderr=subprocess.STDOUT,
                cwd=os.getcwd(),
            )
        self.procs[process_index] = proc

    def kill(self, process_index: int) -> None:
        """SIGKILL one member (chaos path) — no drain, no goodbye."""
        proc = self.procs[process_index]
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    def stop(self, timeout_s: float = 30.0) -> List[Optional[int]]:
        """SIGTERM everyone, wait, SIGKILL stragglers. Returns final
        return codes."""
        for proc in self.procs:
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        for proc in self.procs:
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        return [proc.returncode for proc in self.procs]

    def log_tail(self, chars: int = 2000) -> str:
        """Last ``chars`` of every member's log — the evidence block the
        tests attach to skips and failures."""
        parts = []
        for index, path in enumerate(self._logs):
            try:
                with open(path, "r", encoding="utf-8", errors="replace") as f:
                    text = f.read()
            except OSError:
                text = "<no log>"
            parts.append(f"--- pod proc {index} log tail ---\n{text[-chars:]}")
        return "\n".join(parts)
