"""Pod serving entrypoint: ``python -m client_tpu.pod.worker``.

Every pod member runs this module with its identity in the environment
(the launcher's handoff). All members walk the SAME bootstrap in
lockstep — join ``jax.distributed``, build one tp-sharded
:class:`~client_tpu.llm.serving.LlmEngineModel` over the GLOBAL device
list, run warmup (whose probe device calls are collectives every member
must enter) — and then split:

- **process 0 (coordinator)** opens the step bus, installs the
  bus-broadcast ``device_fn_wrapper`` (each engine device call is
  broadcast to the workers BEFORE the coordinator executes its own
  copy), registers the model, and serves the ordinary HTTP/gRPC
  front-ends. To the fleet this process IS the pod: one replica, one
  model row, with per-member liveness/duty exported as
  ``tpu_pod_process_up`` / ``tpu_pod_process_duty_ratio``.
- **processes 1..N-1 (workers)** run the follower loop: execute every
  broadcast step against their local shards and ack with cumulative
  busy time. They serve no requests and export no metrics of their own.

The model itself deliberately stays the repo's tiny llama (float32 so
tp parity holds to 1e-5): the pod machinery is about WHERE the mesh
lives, not model scale.
"""

import dataclasses
import json
import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from client_tpu.pod.bus import REINIT_OP, StepBus, StepFollower
from client_tpu.pod.runtime import (
    PodConfig,
    PodRuntime,
    initialize,
    reinitialize,
)
from client_tpu.utils import InferenceServerException

ENV_PORTS_FILE = "CLIENT_TPU_POD_PORTS_FILE"
ENV_MODEL_NAME = "CLIENT_TPU_POD_MODEL_NAME"
ENV_MAX_SEQ_LEN = "CLIENT_TPU_POD_MAX_SEQ_LEN"
#: supervisor -> coordinator recovery-plan handoff (JSON file; the
#: supervisor writes {"epoch", "coordinator_address", "member"} and
#: sends SIGUSR1 — see client_tpu.pod.supervisor)
ENV_CONTROL_FILE = "CLIENT_TPU_POD_CONTROL_FILE"


def build_model(runtime: PodRuntime):
    """The pod's model: tiny llama (float32 for tp parity), tp spanning
    the ENTIRE global mesh — which is what makes it unservable by any
    one device-capped member alone."""
    import jax.numpy as jnp

    from client_tpu.llm.serving import LlmEngineModel
    from client_tpu.models import llama

    name = os.environ.get(ENV_MODEL_NAME, "llm_pod")
    max_seq_len = int(os.environ.get(ENV_MAX_SEQ_LEN, "256"))
    config = llama.LlamaConfig.tiny(
        max_seq_len=max_seq_len, dtype=jnp.float32
    )
    model = LlmEngineModel(
        name, config=config, tp=runtime.global_device_count
    )
    # pod supervision owns recovery here: a solo engine reload cannot
    # fix a broken MESH, and the coordinator's recovery procedure
    # (member respawn + jax.distributed re-init + lockstep re-warmup)
    # replaces the tier-1 controller wholesale
    model.auto_recovery = False
    return model


class _Duty:
    """Coordinator-side busy-time accumulator (its own device calls —
    workers report theirs through step acks)."""

    def __init__(self, clock_ns: Callable[[], int] = time.monotonic_ns):
        self._clock_ns = clock_ns
        self.start_ns = clock_ns()
        self.busy_ns = 0
        self._lock = threading.Lock()

    def add(self, ns: int) -> None:
        with self._lock:
            self.busy_ns += ns

    def ratio(self) -> float:
        wall = max(1, self._clock_ns() - self.start_ns)
        with self._lock:
            return self.busy_ns / wall


def make_bus_wrapper(
    bus: StepBus,
    duty: _Duty,
    clock_ns: Callable[[], int] = time.monotonic_ns,
):
    """The coordinator's ``device_fn_wrapper``: broadcast each step's
    host args on the bus, then run the local copy. The broadcast-first
    order is the no-hang guarantee — a dead worker raises a retryable
    UNAVAILABLE here, before this process enters the collective."""
    import jax

    def wrapper(prefill, decode, decode_multi):
        def timed(fn, *args):
            t0 = clock_ns()
            out = fn(*args)
            jax.block_until_ready(out)
            duty.add(clock_ns() - t0)
            return out

        def wrapped_prefill(tokens, page_table, pages, last_index,
                            start_index):
            bus.broadcast(
                "prefill",
                (
                    np.asarray(tokens, np.int32),
                    np.asarray(page_table, np.int32),
                    int(last_index),
                    int(start_index),
                ),
            )
            return timed(
                prefill, tokens, page_table, pages, last_index, start_index
            )

        def wrapped_decode(tokens, positions, page_tables, pages):
            bus.broadcast(
                "decode",
                (
                    np.asarray(tokens, np.int32),
                    np.asarray(positions, np.int32),
                    np.asarray(page_tables, np.int32),
                ),
            )
            return timed(decode, tokens, positions, page_tables, pages)

        wrapped_multi = None
        if decode_multi is not None:
            def wrapped_multi(tokens, positions, lengths, page_tables,
                              pages):
                bus.broadcast(
                    "decode_multi",
                    (
                        np.asarray(tokens, np.int32),
                        np.asarray(positions, np.int32),
                        np.asarray(lengths, np.int32),
                        np.asarray(page_tables, np.int32),
                    ),
                )
                return timed(
                    decode_multi, tokens, positions, lengths, page_tables,
                    pages,
                )

        return wrapped_prefill, wrapped_decode, wrapped_multi

    return wrapper


def follower_handlers(model) -> Dict[str, Callable[..., None]]:
    """A worker's step handler table: each op re-runs the corresponding
    UNWRAPPED device fn against this process's page-pool shards. The
    block_until_ready keeps the ack's busy-time honest (and this member
    from queueing unboundedly far behind the coordinator)."""
    import jax

    prefill, decode, decode_multi = model._device_fns
    state = {"pages": model.engine._pages}

    def on_prefill(tokens, page_table, last_index, start_index):
        logits, state["pages"] = prefill(
            tokens, page_table, state["pages"],
            int(last_index), int(start_index),
        )
        jax.block_until_ready(logits)

    def on_decode(tokens, positions, page_tables):
        logits, state["pages"] = decode(
            tokens, positions, page_tables, state["pages"]
        )
        jax.block_until_ready(logits)

    handlers = {"prefill": on_prefill, "decode": on_decode}
    if decode_multi is not None:
        def on_decode_multi(tokens, positions, lengths, page_tables):
            logits, state["pages"] = decode_multi(
                tokens, positions, lengths, page_tables, state["pages"]
            )
            jax.block_until_ready(logits)

        handlers["decode_multi"] = on_decode_multi
    return handlers


def _start_pod_reporter(
    metrics,
    duty: _Duty,
    get_state: Callable[[], tuple],
    stop: threading.Event,
) -> threading.Thread:
    """Refresh the per-member liveness/duty gauges once a second from
    the bus's ack bookkeeping. ``get_state`` returns the CURRENT
    (bus, runtime) pair — a recovery swaps both out underneath."""

    def run() -> None:
        while not stop.wait(1.0):
            metrics.set_pod_process(0, True, duty.ratio())
            bus, runtime = get_state()
            if bus is None:
                continue
            wall = max(1, duty._clock_ns() - duty.start_ns)
            busy = bus.worker_busy_ns()
            alive = set(bus.alive_workers())
            for index in range(1, runtime.process_count):
                metrics.set_pod_process(
                    index, index in alive, busy.get(index, 0) / wall
                )

    thread = threading.Thread(target=run, name="pod-reporter", daemon=True)
    thread.start()
    return thread


#: How long parked survivors wait for a recovery plan to claim them
#: before the coordinator gives up on rescue.  The supervisor claims
#: them within ~1s of a member death (0.2s poll + plan write + SIGUSR1),
#: so this only fires on an UNsupervised pod — where waiting any longer
#: just turns the quarantine into the hung stream it exists to prevent.
RESCUE_DEADLINE_ENV = "TPU_POD_RESCUE_DEADLINE_S"
_RESCUE_DEADLINE_S = 15.0


def _wire_pod_fatal_hook(engine, holder: dict, quarantined: threading.Event,
                         retry_after_s: float = 2.0,
                         loop=None,
                         clock: Callable[[], float] = time.monotonic) -> None:
    """Make the engine quarantine-not-fail on a fatal: survivors park in
    ``holder["survivors"]`` until the recovered engine adopts them, and
    submits answer 503 + Retry-After while the pod re-assembles.

    The park is deadline-bounded ("hung ≡ killed" applies to rescues
    too): if no recovery plan claims the survivors — ``_recover_pod``
    sets ``holder["rescued"]`` the moment it starts — within the rescue
    deadline, they fail with a clean retryable UNAVAILABLE and the
    engine drops its recovering promise, instead of holding client
    streams open for a supervisor that does not exist."""
    engine.retry_after_s = retry_after_s
    holder.setdefault("lock", threading.Lock())
    rescued = threading.Event()
    holder["rescued"] = rescued
    deadline_s = float(
        os.environ.get(RESCUE_DEADLINE_ENV, "") or _RESCUE_DEADLINE_S
    )

    def abandon(exc: BaseException, started: float) -> None:
        if rescued.wait(deadline_s):
            return
        with holder["lock"]:
            if rescued.is_set():
                return  # a recovery claimed them between wait and lock
            orphans = list(holder["survivors"])
            holder["survivors"][:] = []
        fail = InferenceServerException(
            f"pod quarantined ({exc}) and no recovery plan arrived "
            f"within {deadline_s:.0f}s; resubmit",
            status="UNAVAILABLE",
        )

        def finish() -> None:
            engine.recovering = False
            for seq in orphans:
                seq.fail(fail)

        delivered = False
        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(finish)
                delivered = True
            except RuntimeError:
                pass  # loop closed between the check and the call
        if not delivered:
            finish()
        metrics = getattr(engine, "metrics", None)
        if metrics is not None:
            metrics.observe_recovery("pod", "abandoned", clock() - started)
        print(
            f"pod rescue abandoned: {len(orphans)} parked sequences "
            f"failed after {deadline_s:.0f}s without a recovery plan",
            file=sys.stderr, flush=True,
        )

    def on_fatal(exc: BaseException) -> None:
        holder["survivors"].extend(engine.detach_survivors())
        quarantined.set()
        threading.Thread(
            target=abandon, args=(exc, clock()),
            name="pod-rescue-deadline", daemon=True,
        ).start()

    engine.on_fatal = on_fatal


def _write_ports(server, model, runtime: PodRuntime, epoch: int) -> None:
    from client_tpu.perf.fleet_runner import write_ports_file

    ports_path = os.environ.get(ENV_PORTS_FILE)
    if ports_path:
        write_ports_file(
            ports_path,
            {
                "http_port": server.http_port,
                "grpc_port": server.grpc_port,
                "model": model.name,
                "process_count": runtime.process_count,
                "global_device_count": runtime.global_device_count,
                "local_device_count": runtime.local_device_count,
                "epoch": epoch,
            },
        )


def _recover_pod(model, core, server, state: dict, quarantined:
                 threading.Event, clock: Callable[[], float]) -> bool:
    """The coordinator's half of a supervised pod recovery.

    The supervisor wrote the plan (new coordinator address + epoch) to
    the control file and signalled SIGUSR1.  Sequencing is load-bearing
    (see pod/runtime.py): quarantine → tell survivors where to re-join →
    tear down the old bus → *marker file* (the supervisor's cue to spawn
    the replacement, which must not call initialize before our new
    service exists) → re-init jax.distributed → lockstep re-warmup →
    accept everyone on a fresh bus → adopt the parked survivors.
    Returns False when recovery failed (the pod should exit and let the
    fleet tier replace the whole replica)."""
    from client_tpu.pod.bus import PodWorkerLostError  # noqa: F401

    config: PodConfig = state["config"]
    runtime: PodRuntime = state["runtime"]
    bus: Optional[StepBus] = state["bus"]
    duty: _Duty = state["duty"]
    metrics = core.metrics
    started = clock()
    control_path = os.environ.get(ENV_CONTROL_FILE, "")
    holder = state["holder"]
    # claim the parked survivors FIRST: the fatal hook's rescue-deadline
    # timer fails whatever is still unclaimed when it expires, and this
    # recovery now owns them
    with holder.setdefault("lock", threading.Lock()):
        rescued = holder.get("rescued")
        if rescued is not None:
            rescued.set()
    try:
        with open(control_path, "r", encoding="utf-8") as f:
            plan = json.load(f)
        epoch = int(plan["epoch"])
        new_address = str(plan["coordinator_address"])
        lost = int(plan.get("member", -1))
        print(
            f"pod recovery epoch {epoch}: member {lost} lost, "
            f"re-assembling at {new_address}",
            flush=True,
        )
        core.lifecycle.begin_drain()
        engine = model.engine
        if engine is not None and not engine._closed:
            # idle-pod loss: nothing tripped the step loop, so force the
            # quarantine (parks nothing if nothing was running)
            engine.quarantine(f"pod member {lost} lost")
        if not quarantined.wait(timeout=10.0):
            raise RuntimeError("engine did not quarantine within 10s")
        quarantined.clear()
        if bus is not None:
            # survivors ack, leave their follower loops, and head for
            # the new assembly; the dead member is silently dropped
            bus.broadcast_surviving(REINIT_OP, (new_address, epoch))
            bus.stop()
        # the supervisor's cue: our new coordination service is about to
        # bind, so the replacement process may now be spawned (it takes
        # a full interpreter+jax start to reach initialize — far longer
        # than our service bind)
        with open(control_path + f".started.{epoch}", "w",
                  encoding="utf-8") as f:
            f.write(str(epoch))
        new_config = dataclasses.replace(
            config, coordinator_address=new_address
        )
        runtime = reinitialize(new_config)
        state["config"] = new_config
        state["runtime"] = runtime
        # the old backend's arrays (params, KV pages) died with the old
        # runtime; dropping the cached params makes reload() re-init
        # them from the same PRNGKey(0) — bit-identical, which is what
        # keeps resumed streams token-identical across the respawn
        model._params = None
        with holder["lock"]:
            survivors = list(holder["survivors"])
            holder["survivors"][:] = []
        new_bus = None
        if new_config.process_count > 1:
            new_bus = StepBus(
                num_workers=new_config.process_count - 1,
                address=new_config.bus_address,
            )
            model.device_fn_wrapper = make_bus_wrapper(new_bus, duty)
        state["bus"] = new_bus
        # lockstep point: survivors + replacement mirror these probes
        model.reload()
        if new_bus is not None:
            new_bus.accept_workers()
        model.bind_core(core)
        _wire_pod_fatal_hook(model.engine, holder, quarantined,
                             loop=server._loop)
        if survivors:
            server._loop.call_soon_threadsafe(model.engine.adopt, survivors)
        # the replaced member's gauge children would otherwise linger at
        # their last pre-kill values forever; prune + re-seed
        for index in range(runtime.process_count):
            metrics.prune_pod_process(index)
            metrics.set_pod_process(index, True, 0.0)
        _write_ports(server, model, runtime, epoch)
        core.lifecycle.resume()
        duration = clock() - started
        metrics.observe_recovery("pod", "success", duration)
        print(
            f"pod recovery epoch {epoch} complete in {duration:.2f}s "
            f"({len(survivors)} sequences resumed)",
            flush=True,
        )
        return True
    except Exception as e:  # noqa: BLE001 - recovery is best-effort
        metrics.observe_recovery("pod", "failed", clock() - started)
        print(f"pod recovery failed: {e!r}", file=sys.stderr, flush=True)
        return False


def _serve_coordinator(model, config: PodConfig, runtime: PodRuntime) -> int:
    from client_tpu.server.core import ServerCore
    from client_tpu.server.model_repository import ModelRepository
    from client_tpu.testing.inprocess import InProcessServer

    bus = None
    duty = _Duty()
    if config.process_count > 1:
        bus = StepBus(
            num_workers=config.process_count - 1, address=config.bus_address
        )
        model.device_fn_wrapper = make_bus_wrapper(bus, duty)
    # lockstep point: every member runs warmup's probe collectives now
    model.warmup()
    if bus is not None:
        bus.accept_workers()
    # the repository re-runs warmup on add_model/load — a second probe
    # sequence here would run collectives the workers don't mirror, so
    # the already-warm model's warmup is pinned to a no-op (reload()
    # goes through the class, bypassing this pin on purpose)
    model.warmup = lambda: None  # type: ignore[method-assign]
    repository = ModelRepository()
    core = ServerCore(repository)
    repository.add_model(model)
    server = InProcessServer(
        core=core, builtin_models=False, grpc="aio"
    ).start()
    stop = threading.Event()
    metrics = core.metrics
    metrics.set_pod_process(0, True, 0.0)
    if bus is not None:
        for index in range(1, runtime.process_count):
            metrics.set_pod_process(index, True, 0.0)
    # supervised-recovery state: the fatal hook parks surviving
    # sequences; SIGUSR1 runs the recovery plan from the control file
    holder = {"survivors": []}
    quarantined = threading.Event()
    _wire_pod_fatal_hook(model.engine, holder, quarantined,
                         loop=server._loop)
    state = {
        "config": config, "runtime": runtime, "bus": bus, "duty": duty,
        "holder": holder,
    }
    reporter_state = lambda: (state["bus"], state["runtime"])  # noqa: E731
    _start_pod_reporter(metrics, duty, reporter_state, stop)
    _write_ports(server, model, runtime, epoch=0)
    print(
        f"pod coordinator up: {runtime.process_count} processes, "
        f"{runtime.global_device_count} global devices, "
        f"http={server.http_port} grpc={server.grpc_port}",
        flush=True,
    )
    wake = threading.Event()
    flags = {"stop": False, "recover": False}

    def on_stop(*_args) -> None:
        flags["stop"] = True
        wake.set()

    def on_recover(*_args) -> None:
        flags["recover"] = True
        wake.set()

    signal.signal(signal.SIGTERM, on_stop)
    signal.signal(signal.SIGINT, on_stop)
    signal.signal(signal.SIGUSR1, on_recover)
    rc = 0
    while True:
        wake.wait()
        wake.clear()
        if flags["stop"]:
            break
        if flags["recover"]:
            flags["recover"] = False
            if not _recover_pod(model, core, server, state, quarantined,
                                clock=time.monotonic):
                rc = 3
                break
    stop.set()
    if state["bus"] is not None:
        state["bus"].stop()
    # pod shutdown: drop every member's gauge children so a scrape of a
    # half-stopped coordinator never shows stale liveness
    for index in range(state["runtime"].process_count):
        metrics.prune_pod_process(index)
    server.stop()
    return rc


def _follow_worker(model, config: PodConfig) -> int:
    # lockstep point: mirrors the coordinator's warmup collectives
    model.warmup()
    follower = StepFollower(config.bus_address, config.process_index)
    while True:
        print(
            f"pod worker {config.process_index} following "
            f"{config.bus_address}",
            flush=True,
        )
        reason = follower.follow(follower_handlers(model))
        if reason != "reinit":
            print(
                f"pod worker {config.process_index} done: {reason}",
                flush=True,
            )
            follower.close()
            return 0
        # a surviving member's half of a supervised recovery: the
        # coordinator told us where the NEW assembly lives; mirror its
        # sequence — abandon the broken runtime, re-join at the new
        # address, rebuild the model (old backend arrays died with the
        # old runtime), re-enter the lockstep warmup probes, and rejoin
        # the bus (whose connect retries cover the coordinator's
        # re-warmup window)
        new_address, epoch = follower.reinit_args
        follower.close()
        print(
            f"pod worker {config.process_index} re-joining epoch {epoch} "
            f"at {new_address}",
            flush=True,
        )
        config = dataclasses.replace(
            config, coordinator_address=str(new_address)
        )
        runtime = reinitialize(config)
        model = build_model(runtime)
        model.warmup()
        follower = StepFollower(config.bus_address, config.process_index)


def main() -> int:
    config = PodConfig.from_env()
    if config is None:
        print(
            "not a pod member: CLIENT_TPU_POD_COORDINATOR is unset "
            "(use client_tpu.pod.PodLauncher)",
            file=sys.stderr,
        )
        return 2
    runtime = initialize(config)
    print(f"pod member up: {runtime.describe()}", flush=True)
    model = build_model(runtime)
    if config.is_coordinator:
        return _serve_coordinator(model, config, runtime)
    if not config.bus_address:
        print("pod worker needs a bus address", file=sys.stderr)
        return 2
    return _follow_worker(model, config)


if __name__ == "__main__":
    sys.exit(main())
