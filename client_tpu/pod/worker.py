"""Pod serving entrypoint: ``python -m client_tpu.pod.worker``.

Every pod member runs this module with its identity in the environment
(the launcher's handoff). All members walk the SAME bootstrap in
lockstep — join ``jax.distributed``, build one tp-sharded
:class:`~client_tpu.llm.serving.LlmEngineModel` over the GLOBAL device
list, run warmup (whose probe device calls are collectives every member
must enter) — and then split:

- **process 0 (coordinator)** opens the step bus, installs the
  bus-broadcast ``device_fn_wrapper`` (each engine device call is
  broadcast to the workers BEFORE the coordinator executes its own
  copy), registers the model, and serves the ordinary HTTP/gRPC
  front-ends. To the fleet this process IS the pod: one replica, one
  model row, with per-member liveness/duty exported as
  ``tpu_pod_process_up`` / ``tpu_pod_process_duty_ratio``.
- **processes 1..N-1 (workers)** run the follower loop: execute every
  broadcast step against their local shards and ack with cumulative
  busy time. They serve no requests and export no metrics of their own.

The model itself deliberately stays the repo's tiny llama (float32 so
tp parity holds to 1e-5): the pod machinery is about WHERE the mesh
lives, not model scale.
"""

import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from client_tpu.pod.bus import StepBus, StepFollower
from client_tpu.pod.runtime import PodConfig, PodRuntime, initialize

ENV_PORTS_FILE = "CLIENT_TPU_POD_PORTS_FILE"
ENV_MODEL_NAME = "CLIENT_TPU_POD_MODEL_NAME"
ENV_MAX_SEQ_LEN = "CLIENT_TPU_POD_MAX_SEQ_LEN"


def build_model(runtime: PodRuntime):
    """The pod's model: tiny llama (float32 for tp parity), tp spanning
    the ENTIRE global mesh — which is what makes it unservable by any
    one device-capped member alone."""
    import jax.numpy as jnp

    from client_tpu.llm.serving import LlmEngineModel
    from client_tpu.models import llama

    name = os.environ.get(ENV_MODEL_NAME, "llm_pod")
    max_seq_len = int(os.environ.get(ENV_MAX_SEQ_LEN, "256"))
    config = llama.LlamaConfig.tiny(
        max_seq_len=max_seq_len, dtype=jnp.float32
    )
    return LlmEngineModel(
        name, config=config, tp=runtime.global_device_count
    )


class _Duty:
    """Coordinator-side busy-time accumulator (its own device calls —
    workers report theirs through step acks)."""

    def __init__(self, clock_ns: Callable[[], int] = time.monotonic_ns):
        self._clock_ns = clock_ns
        self.start_ns = clock_ns()
        self.busy_ns = 0
        self._lock = threading.Lock()

    def add(self, ns: int) -> None:
        with self._lock:
            self.busy_ns += ns

    def ratio(self) -> float:
        wall = max(1, self._clock_ns() - self.start_ns)
        with self._lock:
            return self.busy_ns / wall


def make_bus_wrapper(
    bus: StepBus,
    duty: _Duty,
    clock_ns: Callable[[], int] = time.monotonic_ns,
):
    """The coordinator's ``device_fn_wrapper``: broadcast each step's
    host args on the bus, then run the local copy. The broadcast-first
    order is the no-hang guarantee — a dead worker raises a retryable
    UNAVAILABLE here, before this process enters the collective."""
    import jax

    def wrapper(prefill, decode, decode_multi):
        def timed(fn, *args):
            t0 = clock_ns()
            out = fn(*args)
            jax.block_until_ready(out)
            duty.add(clock_ns() - t0)
            return out

        def wrapped_prefill(tokens, page_table, pages, last_index,
                            start_index):
            bus.broadcast(
                "prefill",
                (
                    np.asarray(tokens, np.int32),
                    np.asarray(page_table, np.int32),
                    int(last_index),
                    int(start_index),
                ),
            )
            return timed(
                prefill, tokens, page_table, pages, last_index, start_index
            )

        def wrapped_decode(tokens, positions, page_tables, pages):
            bus.broadcast(
                "decode",
                (
                    np.asarray(tokens, np.int32),
                    np.asarray(positions, np.int32),
                    np.asarray(page_tables, np.int32),
                ),
            )
            return timed(decode, tokens, positions, page_tables, pages)

        wrapped_multi = None
        if decode_multi is not None:
            def wrapped_multi(tokens, positions, lengths, page_tables,
                              pages):
                bus.broadcast(
                    "decode_multi",
                    (
                        np.asarray(tokens, np.int32),
                        np.asarray(positions, np.int32),
                        np.asarray(lengths, np.int32),
                        np.asarray(page_tables, np.int32),
                    ),
                )
                return timed(
                    decode_multi, tokens, positions, lengths, page_tables,
                    pages,
                )

        return wrapped_prefill, wrapped_decode, wrapped_multi

    return wrapper


def follower_handlers(model) -> Dict[str, Callable[..., None]]:
    """A worker's step handler table: each op re-runs the corresponding
    UNWRAPPED device fn against this process's page-pool shards. The
    block_until_ready keeps the ack's busy-time honest (and this member
    from queueing unboundedly far behind the coordinator)."""
    import jax

    prefill, decode, decode_multi = model._device_fns
    state = {"pages": model.engine._pages}

    def on_prefill(tokens, page_table, last_index, start_index):
        logits, state["pages"] = prefill(
            tokens, page_table, state["pages"],
            int(last_index), int(start_index),
        )
        jax.block_until_ready(logits)

    def on_decode(tokens, positions, page_tables):
        logits, state["pages"] = decode(
            tokens, positions, page_tables, state["pages"]
        )
        jax.block_until_ready(logits)

    handlers = {"prefill": on_prefill, "decode": on_decode}
    if decode_multi is not None:
        def on_decode_multi(tokens, positions, lengths, page_tables):
            logits, state["pages"] = decode_multi(
                tokens, positions, lengths, page_tables, state["pages"]
            )
            jax.block_until_ready(logits)

        handlers["decode_multi"] = on_decode_multi
    return handlers


def _start_pod_reporter(
    metrics,
    bus: Optional[StepBus],
    duty: _Duty,
    runtime: PodRuntime,
    stop: threading.Event,
) -> threading.Thread:
    """Refresh the per-member liveness/duty gauges once a second from
    the bus's ack bookkeeping."""

    def run() -> None:
        while not stop.wait(1.0):
            metrics.set_pod_process(0, True, duty.ratio())
            if bus is None:
                continue
            wall = max(1, duty._clock_ns() - duty.start_ns)
            busy = bus.worker_busy_ns()
            alive = set(bus.alive_workers())
            for index in range(1, runtime.process_count):
                metrics.set_pod_process(
                    index, index in alive, busy.get(index, 0) / wall
                )

    thread = threading.Thread(target=run, name="pod-reporter", daemon=True)
    thread.start()
    return thread


def _serve_coordinator(model, config: PodConfig, runtime: PodRuntime) -> int:
    from client_tpu.perf.fleet_runner import write_ports_file
    from client_tpu.server.core import ServerCore
    from client_tpu.server.model_repository import ModelRepository
    from client_tpu.testing.inprocess import InProcessServer

    bus = None
    duty = _Duty()
    if config.process_count > 1:
        bus = StepBus(
            num_workers=config.process_count - 1, address=config.bus_address
        )
        model.device_fn_wrapper = make_bus_wrapper(bus, duty)
    # lockstep point: every member runs warmup's probe collectives now
    model.warmup()
    if bus is not None:
        bus.accept_workers()
    # the repository re-runs warmup on add_model/load — a second probe
    # sequence here would run collectives the workers don't mirror, so
    # the already-warm model's warmup is pinned to a no-op
    model.warmup = lambda: None  # type: ignore[method-assign]
    repository = ModelRepository()
    core = ServerCore(repository)
    repository.add_model(model)
    server = InProcessServer(
        core=core, builtin_models=False, grpc="aio"
    ).start()
    stop = threading.Event()
    metrics = core.metrics
    metrics.set_pod_process(0, True, 0.0)
    if bus is not None:
        for index in range(1, runtime.process_count):
            metrics.set_pod_process(index, True, 0.0)
    _start_pod_reporter(metrics, bus, duty, runtime, stop)
    ports_path = os.environ.get(ENV_PORTS_FILE)
    if ports_path:
        write_ports_file(
            ports_path,
            {
                "http_port": server.http_port,
                "grpc_port": server.grpc_port,
                "model": model.name,
                "process_count": runtime.process_count,
                "global_device_count": runtime.global_device_count,
                "local_device_count": runtime.local_device_count,
            },
        )
    print(
        f"pod coordinator up: {runtime.process_count} processes, "
        f"{runtime.global_device_count} global devices, "
        f"http={server.http_port} grpc={server.grpc_port}",
        flush=True,
    )
    signal.signal(signal.SIGTERM, lambda *_args: stop.set())
    signal.signal(signal.SIGINT, lambda *_args: stop.set())
    stop.wait()
    if bus is not None:
        bus.stop()
    server.stop()
    return 0


def _follow_worker(model, config: PodConfig) -> int:
    # lockstep point: mirrors the coordinator's warmup collectives
    model.warmup()
    follower = StepFollower(config.bus_address, config.process_index)
    print(
        f"pod worker {config.process_index} following "
        f"{config.bus_address}",
        flush=True,
    )
    reason = follower.follow(follower_handlers(model))
    print(f"pod worker {config.process_index} done: {reason}", flush=True)
    follower.close()
    return 0


def main() -> int:
    config = PodConfig.from_env()
    if config is None:
        print(
            "not a pod member: CLIENT_TPU_POD_COORDINATOR is unset "
            "(use client_tpu.pod.PodLauncher)",
            file=sys.stderr,
        )
        return 2
    runtime = initialize(config)
    print(f"pod member up: {runtime.describe()}", flush=True)
    model = build_model(runtime)
    if config.is_coordinator:
        return _serve_coordinator(model, config, runtime)
    if not config.bus_address:
        print("pod worker needs a bus address", file=sys.stderr)
        return 2
    return _follow_worker(model, config)


if __name__ == "__main__":
    sys.exit(main())
