"""Pod supervision: member death -> coordinated restart, with MTTR.

Tier 2 of the self-healing stack.  :class:`PodSupervisor` runs in the
LAUNCHER process (the only process holding the members' ``Popen``
handles) and watches for dead members.  Detection is two-sided by
design: the parent sees a SIGKILLed member instantly via ``poll()``,
while a *hung* member only surfaces inside the coordinator — as the
step bus's ack deadline (``PodWorkerLostError(reason="ack_timeout")``)
quarantining the engine.  Either way the pod cannot run another SPMD
step until the member is replaced and ``jax.distributed`` re-assembled,
which is what :meth:`recover` orchestrates:

1. pick a NEW coordinator address (the abandoned service may still hold
   the old port) and bump the recovery epoch,
2. write the plan (epoch, new address, lost member) to the launcher's
   control file and SIGUSR1 the coordinator,
3. wait for the coordinator's ``.started.<epoch>`` marker — the cue
   that its replacement coordination service is coming up, so a freshly
   spawned member won't fatally time out registering against nothing,
4. respawn the dead member with the new coordinator address,
5. poll the ports file until the coordinator republishes it stamped
   with the new epoch — the pod is serving again; the elapsed time is
   the MTTR sample recorded in :attr:`events`.

The coordinator itself (member 0) is NOT recoverable from here — it
holds the engine state and the front-end sockets; its death is a
replica death, which the fleet tier (``perf/fleet_runner.Autoscaler``
liveness replacement) handles by replacing the whole pod.

Clock/sleep are injected per the repo's clock-lint rules.
"""

import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from client_tpu.perf.fleet_runner import read_ports_file, write_ports_file
from client_tpu.pod.launcher import PodLauncher, _free_port


class PodSupervisor:
    """Watches a :class:`PodLauncher`'s members and replaces dead ones.

    ``deadline_s`` bounds one recovery end to end (the chaos acceptance
    criterion: the pod must serve again within it).  ``on_event`` (when
    set) is called with each recovery event dict as it completes.
    """

    def __init__(
        self,
        launcher: PodLauncher,
        poll_interval_s: float = 0.25,
        deadline_s: float = 240.0,
        on_event: Optional[Callable[[Dict[str, Any]], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.launcher = launcher
        self.poll_interval_s = poll_interval_s
        self.deadline_s = deadline_s
        self.on_event = on_event
        self._clock = clock
        self._sleep = sleep
        self.epoch = 0
        self.events: List[Dict[str, Any]] = []
        self.coordinator_lost = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- watch loop ----------------------------------------------------------

    def start(self) -> "PodSupervisor":
        self._thread = threading.Thread(
            target=self._run, name="pod-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            dead = self.check_once()
            if dead is None:
                continue
            if dead == 0:
                # the coordinator died: not recoverable in-pod (engine
                # state and front-end sockets died with it) — surface
                # for the fleet tier and stand down
                self.coordinator_lost = True
                self._record(
                    member=0, epoch=self.epoch, outcome="coordinator_lost",
                    duration_s=0.0,
                )
                return
            self.recover(dead)

    def check_once(self) -> Optional[int]:
        """The lowest dead member index, or None while all are alive."""
        for index, rc in enumerate(self.launcher.poll()):
            if rc is not None:
                return index
        return None

    # -- coordinated restart -------------------------------------------------

    def recover(self, member: int) -> Dict[str, Any]:
        """Run one coordinated restart for a dead non-coordinator
        member; returns (and records) the recovery event with its MTTR.
        Failure is an event with ``outcome="failed"``, never a raise —
        the watch loop (and the fleet tier above it) decides what a
        failed pod recovery escalates to."""
        started = self._clock()
        self.epoch += 1
        epoch = self.epoch
        host = self.launcher.host
        new_address = f"{host}:{_free_port(host)}"
        write_ports_file(
            self.launcher.control_file,
            {
                "epoch": epoch,
                "coordinator_address": new_address,
                "member": member,
            },
        )
        coordinator = self.launcher.procs[0]
        try:
            coordinator.send_signal(signal.SIGUSR1)
        except OSError:
            self.coordinator_lost = True
            return self._record(
                member=member, epoch=epoch, outcome="coordinator_lost",
                duration_s=self._clock() - started,
            )
        deadline = started + self.deadline_s
        marker = self.launcher.control_file + f".started.{epoch}"
        while self._clock() < deadline and not os.path.exists(marker):
            if coordinator.poll() is not None:
                self.coordinator_lost = True
                return self._record(
                    member=member, epoch=epoch, outcome="coordinator_lost",
                    duration_s=self._clock() - started,
                )
            self._sleep(0.05)
        if not os.path.exists(marker):
            return self._record(
                member=member, epoch=epoch, outcome="failed",
                duration_s=self._clock() - started,
                detail="coordinator never acknowledged the recovery plan",
            )
        # the replacement joins the NEW assembly: move the launcher's
        # coordinator address so _child_env hands it the right target
        self.launcher.coordinator_address = new_address
        self.launcher.respawn(member)
        while self._clock() < deadline:
            ports = read_ports_file(self.launcher.ports_file)
            if ports is not None and int(ports.get("epoch", -1)) == epoch:
                return self._record(
                    member=member, epoch=epoch, outcome="success",
                    duration_s=self._clock() - started,
                )
            if self.launcher.procs[member].poll() is not None:
                return self._record(
                    member=member, epoch=epoch, outcome="failed",
                    duration_s=self._clock() - started,
                    detail=f"replacement member {member} exited rc="
                    f"{self.launcher.procs[member].returncode}",
                )
            self._sleep(0.05)
        return self._record(
            member=member, epoch=epoch, outcome="failed",
            duration_s=self._clock() - started,
            detail="pod did not republish ports within the deadline",
        )

    def _record(self, **event: Any) -> Dict[str, Any]:
        self.events.append(event)
        if self.on_event is not None:
            try:
                self.on_event(dict(event))
            except Exception:  # noqa: BLE001 - observer must not break us
                pass
        return event

    # -- introspection -------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "events": list(self.events),
            "coordinator_lost": self.coordinator_lost,
            "mttr_s": [
                e["duration_s"] for e in self.events
                if e.get("outcome") == "success"
            ],
        }
