"""Python bridge for the native C++ gRPC front-end.

The extension module (native/frontend/grpc_frontend.cc, built as
``_native_frontend.so``) owns the sockets, HTTP/2 framing, HPACK, flow
control, and protobuf parsing on C++ threads; this bridge is the narrow
GIL-bound slice per request:

* a single pump thread drains batches of parsed requests from the C++
  queue (``wait_requests``, GIL released while blocked) and schedules the
  whole batch onto the core's event loop with ONE wakeup — reader threads
  never touch the GIL, and per-request bridge cost amortizes under load;
* request tensors arrive as numpy views (zero-copy into the C++ request
  buffers, which live until the final ``complete`` for the handle);
* ``complete`` (event loop -> C++): hand back output ndarrays; C++ copies
  them while serializing the response and frees the request.
* ``rpc`` (C++ reader thread -> here): non-inference methods, answered by
  :mod:`client_tpu.server._grpc_codec` on the event loop.

This replaces the grpc.aio front-end on the hot path — measured ~2 ms of
per-request Python/grpc-machinery overhead (PERF.md) — while remaining
wire-compatible with every gRPC client, including grpc/grpcio and this
repo's own h2 C++ client.
"""

import asyncio
import os
import sys
import threading
import traceback
from typing import Any, Dict, Optional

import numpy as np

from client_tpu.server import _grpc_codec as codec
from client_tpu.server import shm_ring as ring_codec
from client_tpu.server.core import (
    CoreRequest,
    CoreRequestedOutput,
    CoreResponse,
    CoreTensor,
    ServerCore,
)
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)

_native = None
_native_error: Optional[str] = None


def _load_native():
    """Import the _native_frontend extension, searching the package dir
    (wheel layout) then the repo build tree."""
    global _native, _native_error
    if _native is not None or _native_error is not None:
        return _native
    import importlib.machinery
    import importlib.util

    here = os.path.dirname(os.path.abspath(__file__))
    package_root = os.path.dirname(here)
    repo_root = os.path.dirname(package_root)
    candidates = [
        os.path.join(package_root, "_native_frontend.so"),
        os.path.join(repo_root, "build", "_native_frontend.so"),
    ]
    for path in candidates:
        if not os.path.exists(path):
            continue
        loader = importlib.machinery.ExtensionFileLoader(
            "client_tpu._native_frontend", path
        )
        spec = importlib.util.spec_from_file_location(
            "client_tpu._native_frontend", path, loader=loader
        )
        module = importlib.util.module_from_spec(spec)
        try:
            loader.exec_module(module)
        except ImportError as e:
            _native_error = str(e)
            return None
        sys.modules["client_tpu._native_frontend"] = module
        _native = module
        return _native
    _native_error = "no _native_frontend.so found (build native/ first)"
    return None


def native_available() -> bool:
    return _load_native() is not None


class NativeGrpcFrontend:
    """The native gRPC server bound to one ServerCore + event loop."""

    def __init__(self, core: ServerCore, loop: asyncio.AbstractEventLoop):
        lib = _load_native()
        if lib is None:
            raise RuntimeError(
                f"native frontend unavailable: {_native_error}"
            )
        self._lib = lib
        self._core = core
        self._loop = loop
        self._id: Optional[int] = None
        self.port: Optional[int] = None
        # handle -> asyncio.Task; loop-thread only (cancel hops onto the
        # loop), so no lock is needed.
        self._tasks: Dict[int, Any] = {}
        self._pump: Optional[threading.Thread] = None
        # Pump batch size: bounds the per-wakeup GIL slice. 128 keeps the
        # loop responsive while amortizing the wakeup under load.
        self._batch = 128

    # -- lifecycle -----------------------------------------------------------

    def start(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
    ) -> None:
        """Bind + serve. With ``tls_cert``/``tls_key`` (PEM paths) the
        C++ listener terminates TLS itself (ALPN h2) — grpcs clients
        connect directly, no fronting proxy needed."""
        self._id = self._lib.start(
            host, port, self._rpc, self._cancel, tls_cert, tls_key
        )
        self.port = self._lib.port(self._id)
        self._pump = threading.Thread(
            target=self._pump_loop, name="ctpu-grpc-pump", daemon=True
        )
        self._pump.start()

    def stop(self) -> None:
        if self._id is not None:
            fid, self._id = self._id, None
            self._lib.stop(fid)
            if self._pump is not None:
                self._pump.join(timeout=10)
                self._pump = None

    # -- request path --------------------------------------------------------

    def _pump_loop(self) -> None:
        """Drain parsed requests from C++ in batches. Unary requests run
        RIGHT HERE on the pump thread through ServerCore.infer_direct —
        no event-loop crossing, no per-request future/task/executor hop
        (PERF.md: that asyncio machinery was the dominant per-request
        server cost). Streaming requests hop to the event loop; while a
        direct batch executes, new arrivals queue in C++ and become the
        next batch — the dynamic-batching window.

        wait_requests blocks with the GIL released."""
        try:
            import ctypes

            libc = ctypes.CDLL(None)
            libc.pthread_self.restype = ctypes.c_void_p
            libc.pthread_setname_np.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
            ]
            libc.pthread_setname_np(libc.pthread_self(), b"ctpu-grpc-pump")
        except Exception:  # noqa: BLE001 - naming is best-effort
            pass
        fid = self._id
        while True:
            batch = self._lib.wait_requests(fid, self._batch, 200)
            if batch is None:
                return  # frontend stopped
            if not batch:
                continue
            streaming_items = [item for item in batch if item[7]]
            if streaming_items:
                try:
                    self._loop.call_soon_threadsafe(
                        self._submit_batch, streaming_items
                    )
                except RuntimeError:  # loop closed under us
                    for item in streaming_items:
                        self._complete_error(
                            item[0],
                            "server shutting down",
                            codec.GRPC_UNAVAILABLE,
                        )
            if len(streaming_items) != len(batch):
                direct_items = [item for item in batch if not item[7]]
                try:
                    self._run_direct(direct_items)
                except Exception:  # noqa: BLE001 - pump must survive
                    # A failure here is a bridge bug, not a request
                    # error; contain it so the front-end keeps serving,
                    # and fail the affected handles (no-op for any that
                    # already completed).
                    traceback.print_exc()
                    for item in direct_items:
                        try:
                            self._complete_error(
                                item[0],
                                "internal error completing request batch",
                                codec.GRPC_INTERNAL,
                            )
                        except Exception:  # noqa: BLE001
                            pass

    def _build_request(self, item) -> CoreRequest:
        """One wire-request tuple -> CoreRequest (raises on bad input)."""
        (
            _handle,
            model_name,
            model_version,
            request_id,
            inputs,
            outputs,
            params,
            _streaming,
        ) = item
        decode_input = self._core.decode_input
        request = CoreRequest(
            model_name=model_name,
            model_version=model_version,
            id=request_id,
            parameters=params,
        )
        for name, datatype, shape, data, shm in inputs:
            if type(data) is np.ndarray:
                # Fastest path: the C++ side already built the
                # zero-copy view (shape/dtype validated there).
                request.inputs.append(
                    CoreTensor(name, datatype, list(shape), data)
                )
                continue
            if shm is None and data is not None:
                # Hot path: raw bytes -> numpy view. frombuffer /
                # reshape validate the byte count against the shape.
                if datatype == "BYTES":
                    arr = deserialize_bytes_tensor(data).reshape(shape)
                else:
                    np_dtype = triton_to_np_dtype(datatype)
                    if np_dtype is None:
                        raise InferenceServerException(
                            f"unsupported datatype '{datatype}' "
                            f"for input '{name}'"
                        )
                    arr = np.frombuffer(data, dtype=np_dtype).reshape(shape)
                tensor = CoreTensor(name, datatype, list(shape), arr)
            elif shm is not None:
                region, byte_size, offset = shm
                tensor = decode_input(
                    name,
                    datatype,
                    list(shape),
                    shm_region=region,
                    shm_byte_size=int(byte_size),
                    shm_offset=int(offset),
                )
            else:
                raise InferenceServerException(
                    f"input '{name}' has no data (inline, typed "
                    "contents, or shared memory)"
                )
            request.inputs.append(tensor)
        for name, classification, shm in outputs:
            if shm is not None:
                region, byte_size, offset = shm
                request.outputs.append(
                    CoreRequestedOutput(
                        name=name,
                        classification=int(classification),
                        shm_region=region,
                        shm_byte_size=int(byte_size),
                        shm_offset=int(offset),
                    )
                )
            else:
                request.outputs.append(
                    CoreRequestedOutput(
                        name=name, classification=int(classification)
                    )
                )
        # shm-ring requests: inputs view the ring slot, the response
        # goes back into it (ticket on request.shm_ring)
        ring_codec.attach(self._core, request)
        return request


    def _run_direct(self, items) -> None:
        """Pump thread: decode + execute + complete a batch of unary
        requests synchronously (ServerCore.infer_direct). All completions
        for the batch ride ONE complete_many call — the C++ side then
        serializes and writes the whole batch in a single GIL release."""
        handles = []
        requests = []
        completions = []
        prof = self._core.profiling
        # one take() covers this pump batch's decode AND encode brackets
        measured = prof.take()
        decode_cpu0 = prof.cpu_now() if measured else 0
        for item in items:
            try:
                request = self._build_request(item)
            except Exception as e:  # noqa: BLE001 - wire-level badness
                # Decode errors (including numpy size/shape ValueErrors)
                # are the client's fault: INVALID_ARGUMENT.
                completions.append(
                    self._error_completion(
                        item[0], e, default=codec.GRPC_INVALID_ARGUMENT
                    )
                )
                continue
            handles.append(item[0])
            requests.append(request)
        if measured and requests:
            prof.account(
                "frontend_decode",
                prof.cpu_now() - decode_cpu0,
                count=len(requests),
            )
        if requests:
            results = self._core.infer_direct(requests)
            encode_cpu0 = prof.cpu_now() if measured else 0
            log = self._core.logger
            for handle, request, result in zip(handles, requests, results):
                if isinstance(result, Exception):
                    # Execution errors are the server/model's fault:
                    # INTERNAL (matching the event-loop unary path).
                    if request.shm_ring is not None:
                        request.shm_ring.fail()
                    completions.append(
                        self._error_completion(handle, result)
                    )
                else:
                    if request.shm_ring is not None:
                        try:
                            result = request.shm_ring.complete(result)
                        except Exception as e:  # noqa: BLE001 - per-request
                            # a response that doesn't fit its slot fails
                            # THIS request cleanly; co-batched requests
                            # still complete
                            completions.append(
                                self._error_completion(
                                    handle,
                                    e,
                                    default=codec.GRPC_INVALID_ARGUMENT,
                                )
                            )
                            continue
                    if log.verbose_hot:
                        log.verbose(
                            "request",
                            model=result.model_name,
                            protocol="grpc-native",
                            status="ok",
                            request_id=result.id,
                        )
                    completions.append(
                        self._response_completion(handle, result, 1)
                    )
            if measured:
                prof.account(
                    "encode",
                    prof.cpu_now() - encode_cpu0,
                    count=len(requests),
                )
        if completions:
            self._lib.complete_many(completions)

    def _error_completion(
        self, handle: int, e: Exception, default: Optional[int] = None
    ):
        """complete() argument tuple for a failed request. ``default`` is
        the status for non-InferenceServerException errors (INTERNAL when
        unset — execution context)."""
        if isinstance(e, InferenceServerException):
            message = e.message()
            status = codec.status_code_for(message, exc=e)
        else:
            message = str(e)
            status = codec.GRPC_INTERNAL if default is None else default
        log = self._core.logger
        if log.verbose_hot:
            log.verbose(
                "request",
                protocol="grpc-native",
                status="error",
                error=message,
                grpc_status=status,
            )
        return (handle, "", "", "", None, None, 1, message, status)

    def _submit_batch(self, batch) -> None:
        """Event loop: build CoreRequests and start streaming tasks."""
        prof = self._core.profiling
        for item in batch:
            handle = item[0]
            try:
                if prof.take():
                    decode_cpu0 = prof.cpu_now()
                    request = self._build_request(item)
                    prof.account(
                        "frontend_decode", prof.cpu_now() - decode_cpu0
                    )
                else:
                    request = self._build_request(item)
                task = self._loop.create_task(
                    self._run_stream(handle, request)
                )
                self._tasks[handle] = task
                task.add_done_callback(
                    lambda _t, h=handle: self._tasks.pop(h, None)
                )
            except Exception as e:  # noqa: BLE001 - wire-level badness
                self._lib.complete(
                    *self._error_completion(
                        handle, e, default=codec.GRPC_INVALID_ARGUMENT
                    )
                )

    def _cancel(self, handle: int) -> None:
        """C++ thread: peer reset the stream / dropped the connection."""
        try:
            self._loop.call_soon_threadsafe(self._cancel_on_loop, handle)
        except RuntimeError:
            pass
        # Guarantee the native side frees the request even if the task never
        # ran. complete() on an already-finalized handle is a no-op, so a
        # race with normal completion is safe.
        self._complete_error(handle, "request cancelled", 1)

    def _cancel_on_loop(self, handle: int) -> None:
        task = self._tasks.pop(handle, None)
        if task is not None:
            task.cancel()

    # -- completion helpers --------------------------------------------------

    def _complete_error(self, handle: int, message: str, status: int) -> None:
        self._lib.complete(handle, "", "", "", None, None, 1, message, status)

    @staticmethod
    def _payload(tensor) -> np.ndarray:
        if tensor.datatype == "BYTES":
            return serialize_byte_tensor(tensor.data)
        data = tensor.data
        if data.flags.c_contiguous:
            return data  # row slices of a C-contiguous batch land here
        return np.ascontiguousarray(data)

    def _response_completion(
        self, handle: int, response: CoreResponse, final: int
    ):
        """complete() argument tuple for a successful response."""
        outs = []
        for t in response.outputs:
            shm = response.shm_outputs.get(t.name)
            if shm is not None:
                outs.append((t.name, t.datatype, tuple(t.shape), None, shm))
            else:
                outs.append(
                    (
                        t.name,
                        t.datatype,
                        tuple(t.shape),
                        self._payload(t),
                        None,
                    )
                )
        return (
            handle,
            response.model_name,
            response.model_version,
            response.id,
            outs,
            response.parameters or None,
            final,
            None,
            0,
        )

    def _complete_response(
        self, handle: int, response: CoreResponse, final: bool
    ) -> None:
        self._lib.complete(
            *self._response_completion(handle, response, 1 if final else 0)
        )

    # -- per-request coroutines ----------------------------------------------

    async def _run_stream(self, handle: int, request: CoreRequest) -> None:
        """One request on a ModelStreamInfer stream: 0..N responses.

        The native side needs `final` on the LAST response (it frees the
        request buffers there), so responses are sent with one-item
        lookahead.
        """
        held: Optional[CoreResponse] = None
        try:
            if request.shm_ring is not None:
                # ring slots hold exactly one response: unary execution,
                # tensors diverted into the slot, slim ack on the wire
                response = await self._core.infer(request)
                self._complete_response(
                    handle, request.shm_ring.complete(response), final=True
                )
                return
            async for response in self._core.infer_decoupled(request):
                if held is not None:
                    self._complete_response(handle, held, final=False)
                held = response
        except asyncio.CancelledError:
            if request.shm_ring is not None:
                request.shm_ring.fail()
            if not self._core.lifecycle.accepting:
                # torn down by a drain deadline, not by the peer: the
                # client gets a clean retryable UNAVAILABLE, never a
                # bare CANCELLED from a cancelled future
                self._complete_error(
                    handle,
                    "server is draining and not accepting new inference "
                    "requests",
                    codec.GRPC_UNAVAILABLE,
                )
            else:
                self._complete_error(handle, "request cancelled", 1)
            raise
        except InferenceServerException as e:
            if request.shm_ring is not None:
                request.shm_ring.fail()
            self._complete_error(
                handle, e.message(), codec.status_code_for(e.message(), exc=e)
            )
            return
        except Exception as e:  # noqa: BLE001
            if request.shm_ring is not None:
                request.shm_ring.fail()
            self._complete_error(handle, str(e), codec.GRPC_INTERNAL)
            return
        if held is not None:
            self._complete_response(handle, held, final=True)
        else:
            # Zero-response stream: emit Triton's final empty response so
            # the client's request completes.
            empty = CoreResponse(
                model_name=request.model_name,
                model_version=request.model_version,
                id=request.id,
                outputs=[],
                parameters={"triton_final_response": True},
            )
            self._complete_response(handle, empty, final=True)

    # -- non-inference methods ----------------------------------------------

    def _rpc(self, method: str, payload: bytes):
        """C++ reader thread: run a non-inference method on the loop (same
        single-threaded core access as the other front-ends) and block —
        GIL released inside result() — for the answer."""
        try:
            future = asyncio.run_coroutine_threadsafe(
                self._rpc_on_loop(method, payload), self._loop
            )
            return future.result(timeout=120)
        except Exception as e:  # noqa: BLE001 - includes loop shutdown
            return (codec.GRPC_INTERNAL, b"", f"internal error: {e}")

    async def _rpc_on_loop(self, method: str, payload: bytes):
        try:
            return (
                0,
                codec.handle_method_bytes(self._core, method, payload),
                "",
            )
        except codec.RpcError as e:
            return (e.status, b"", e.message)
        except Exception as e:  # noqa: BLE001
            return (codec.GRPC_INTERNAL, b"", str(e))


async def serve_grpc_native(
    core: ServerCore,
    host: str = "0.0.0.0",
    port: int = 8001,
    tls_cert: Optional[str] = None,
    tls_key: Optional[str] = None,
):
    """Start the native gRPC front-end; returns (frontend, bound_port).

    Signature mirrors grpc_server.serve_grpc so callers can switch
    implementations; `frontend.stop()` is synchronous. TLS termination
    (grpcs) is enabled by passing PEM cert/key paths.
    """
    frontend = NativeGrpcFrontend(core, asyncio.get_running_loop())
    frontend.start(host, port, tls_cert=tls_cert, tls_key=tls_key)
    return frontend, frontend.port
