"""Model abstraction + repository for the in-repo server.

A model exposes KServe v2 metadata/config and an execute function over
name->ndarray dicts. Decoupled (streaming) models yield multiple responses
per request via an async generator, mirroring Triton's decoupled transaction
policy (reference model_config.proto ModelTransactionPolicy).
"""

import importlib.util
import json
import os
import threading
from typing import Any, AsyncIterator, Dict, List, Optional

import numpy as np

from client_tpu.utils import InferenceServerException

# index() states (Triton RepositoryIndex wire values)
STATE_READY = "READY"
STATE_UNAVAILABLE = "UNAVAILABLE"
STATE_LOADING = "LOADING"
STATE_UNLOADING = "UNLOADING"


class ModelUnavailableError(InferenceServerException):
    """A request targeted a model that exists but is not serving
    (unloaded, unloading, or load-failed).

    Carries both wire faces directly — HTTP 503 (a retryable status, so
    clients with a retry policy ride through an unload->load window) and
    gRPC UNAVAILABLE — instead of the generic 400/INVALID_ARGUMENT a
    missing model gets: "temporarily gone" and "never existed" are
    different contracts."""

    http_status = 503
    grpc_code = "UNAVAILABLE"

    def __init__(self, msg: str):
        super().__init__(msg, status="UNAVAILABLE")


def _mesh_capacity_failure(exc: Optional[BaseException]) -> bool:
    """True when a load failure is a mesh-capacity problem ("mesh
    requires N devices, host has M") anywhere in the cause chain — a
    property of the host, not a broken model, so it must not degrade
    whole-server readiness the way corrupt weights do."""
    try:
        from client_tpu.parallel.sharding import MeshUnavailableError
    except Exception:  # noqa: BLE001 - parallel layer optional at import
        return False
    seen = set()
    while exc is not None and id(exc) not in seen:
        if isinstance(exc, MeshUnavailableError):
            return True
        seen.add(id(exc))
        exc = exc.__cause__
    return False


class Model:
    """Base class for served models.

    Subclasses define ``inputs``/``outputs`` metadata and implement
    :meth:`execute` (one response) or :meth:`execute_decoupled` (stream of
    responses; set ``decoupled = True``).
    """

    name: str = "model"
    version: str = "1"
    platform: str = "jax"
    backend: str = "jax"
    max_batch_size: int = 0
    decoupled: bool = False
    # Placement hint: "" = framework default (the accelerator), "cpu" = the
    # host JAX backend. Tiny elementwise models should be host-placed: a
    # TPU-relay round-trip costs a flat ~67 ms per readback (PERF.md), so
    # only models with real FLOPs (conv/matmul) earn the trip.
    device: str = ""
    # [{"name", "datatype", "shape"}] — shape without batch dim if
    # max_batch_size > 0, matching Triton config conventions.
    inputs: List[Dict[str, Any]] = []
    outputs: List[Dict[str, Any]] = []
    # Mixed-shape dynamic batching (the server-side half of Triton's ragged
    # batching, reference docs ragged_batching.md): when True, concurrent
    # requests whose shapes differ ONLY in dims the model declares as -1
    # share one execution — the batcher zero-pads those dims to a shared
    # power-of-two bucket (bounding XLA retraces) before concatenating.
    # The model must tolerate padding (e.g. mask pad_token positions).
    allow_ragged_batch: bool = False
    ragged_pad_value: int = 0
    # Hard upper bound for padded ragged dims (e.g. max sequence length);
    # the batcher clamps its power-of-two bucket here so merging can never
    # push a batch past a limit its members individually respect.
    ragged_dim_cap: Optional[int] = None
    # Scheduler declarations, surfaced through the model-configuration
    # extension so clients (perf_analyzer's ModelParser, reference
    # model_parser.cc scheduler-kind detection) can auto-detect how to
    # drive the model. dynamic_batching is emitted automatically for
    # batchable models (the core batcher is always on for them).
    sequence_batching: Optional[Dict[str, Any]] = None
    ensemble_scheduling: Optional[Dict[str, Any]] = None
    # Admission control (client_tpu.scheduling; the ModelDynamicBatching
    # priority / ModelQueuePolicy / ModelRateLimiter surface):
    # priority_levels N declares queue levels 1..N (1 = highest);
    # requests without a priority parameter land on default_priority_level
    # (or the lowest level when 0). queue_policy keys: max_queue_size,
    # default_timeout_us, timeout_action ("reject"|"continue"),
    # allow_timeout_override. rate_limiter: {"resources": [{"name",
    # "count"}], "priority"} — executions acquire those pool resources.
    priority_levels: int = 0
    default_priority_level: int = 0
    queue_policy: Optional[Dict[str, Any]] = None
    rate_limiter: Optional[Dict[str, Any]] = None
    # Sharded execution (client_tpu.parallel.sharding): a mesh
    # declaration {"axes": {"dp": 2, "tp": 2}, "inputs": {name: spec},
    # "outputs": {name: spec}} resolved against jax.devices() at
    # load/warmup time into a Mesh + per-tensor NamedShardings. Models
    # that resolve one publish the live plan as ``mesh_plan`` (used by
    # debug_state()'s devices block and per-device busy accounting). A
    # host with too few devices surfaces the model as UNAVAILABLE with
    # reason "load failed: mesh requires N devices, host has M".
    mesh: Optional[Dict[str, Any]] = None
    mesh_plan: Optional[Any] = None

    def metadata(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "versions": [self.version],
            "platform": self.platform,
            "inputs": [
                {
                    "name": i["name"],
                    "datatype": i["datatype"],
                    "shape": ([-1] if self.max_batch_size > 0 else [])
                    + list(i["shape"]),
                }
                for i in self.inputs
            ],
            "outputs": [
                {
                    "name": o["name"],
                    "datatype": o["datatype"],
                    "shape": ([-1] if self.max_batch_size > 0 else [])
                    + list(o["shape"]),
                }
                for o in self.outputs
            ],
        }

    def config(self) -> Dict[str, Any]:
        config = {
            "name": self.name,
            "platform": self.platform,
            "backend": self.backend,
            "max_batch_size": self.max_batch_size,
            "input": [
                {
                    "name": i["name"],
                    "data_type": "TYPE_" + i["datatype"].replace("BYTES", "STRING"),
                    "dims": list(i["shape"]),
                }
                for i in self.inputs
            ],
            "output": [
                {
                    "name": o["name"],
                    "data_type": "TYPE_" + o["datatype"].replace("BYTES", "STRING"),
                    "dims": list(o["shape"]),
                }
                for o in self.outputs
            ],
            "model_transaction_policy": {"decoupled": self.decoupled},
        }
        if self.sequence_batching is not None:
            config["sequence_batching"] = dict(self.sequence_batching)
        elif self.max_batch_size > 1 and self.ensemble_scheduling is None:
            # Batchable models ride the core's dynamic batcher; declare it
            # the way Triton configs do so clients can see the scheduler.
            # Ensembles never declare it (the proto's scheduling_choice is
            # a oneof — both protocols must report the same scheduler).
            dynamic_batching: Dict[str, Any] = {}
            if self.priority_levels:
                dynamic_batching["priority_levels"] = self.priority_levels
                dynamic_batching["default_priority_level"] = (
                    self.default_priority_level
                )
            if self.queue_policy:
                qp = self.queue_policy
                # Triton wire names (ModelQueuePolicy)
                dynamic_batching["default_queue_policy"] = {
                    "timeout_action": (
                        "DELAY"
                        if qp.get("timeout_action") == "continue"
                        else "REJECT"
                    ),
                    "default_timeout_microseconds": int(
                        qp.get("default_timeout_us", 0)
                    ),
                    "allow_timeout_override": bool(
                        qp.get("allow_timeout_override", True)
                    ),
                    "max_queue_size": int(qp.get("max_queue_size", 0)),
                }
            config["dynamic_batching"] = dynamic_batching
        if self.rate_limiter:
            config["rate_limiter"] = {
                "resources": [
                    dict(r) for r in self.rate_limiter.get("resources", [])
                ],
                "priority": int(self.rate_limiter.get("priority", 0)),
            }
        if self.ensemble_scheduling is not None:
            config["ensemble_scheduling"] = {
                "step": [dict(s) for s in
                         self.ensemble_scheduling.get("step", [])]
            }
        if isinstance(self.mesh, dict):
            # Mesh topology rides the config's parameters map (Triton
            # ModelParameter wire shape: {"string_value": ...}) so BOTH
            # protocols expose it — the gRPC ServerMetadataResponse has
            # no free-form field, the ModelConfig parameters map does.
            # A resolved plan reports the live topology (device ids
            # included); an unresolved declaration reports what was
            # asked for.
            plan = self.mesh_plan
            payload = (
                plan.describe()
                if plan is not None
                else {"axes": dict(self.mesh.get("axes", {})), "resolved": False}
            )
            config["parameters"] = {
                "mesh": {"string_value": json.dumps(payload)}
            }
        return config

    def labels(self, output_name: str) -> Optional[List[str]]:
        """Classification labels for an output (None if unlabeled)."""
        return None

    def placement(self):
        """Context manager placing this model's JAX work per ``device``.

        Honored by the server core around execute() and usable from
        warmup(). Falls back to the default device when the requested
        backend is unavailable (e.g. jax_platforms pinned away from cpu).
        """
        import contextlib

        if self.device == "cpu":
            try:
                import jax

                return jax.default_device(jax.devices("cpu")[0])
            except Exception:  # noqa: BLE001 - backend unavailable
                pass
        return contextlib.nullcontext()

    def execute(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> Dict[str, np.ndarray]:
        raise InferenceServerException(
            f"model '{self.name}' does not implement execute"
        )

    async def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> AsyncIterator[Dict[str, np.ndarray]]:
        raise InferenceServerException(
            f"model '{self.name}' is not decoupled"
        )
        yield {}  # pragma: no cover - makes this an async generator

    def warmup(self) -> None:
        """Called at load; jit-compile here so first request is fast."""


class ModelRepository:
    """Name -> model registry with Triton-style load/unload semantics.

    Models can be registered programmatically (``add_model``) or loaded from
    a repository directory where each subdirectory holds a ``model.py``
    defining ``create_model()`` (the python_backend analogue).
    """

    def __init__(self, repository_path: Optional[str] = None):
        self._models: Dict[str, Model] = {}
        self._state: Dict[str, str] = {}
        self._reason: Dict[str, str] = {}
        # per-name load/unload generation: async unload finalization and
        # batcher eviction only apply when no load() happened in between
        self._epoch: Dict[str, int] = {}
        # names whose "load failed" is a host-capacity (mesh) problem:
        # excluded from degraded() so one oversized mesh never pulls the
        # whole replica out of its load balancer
        self._capacity_failed: set = set()
        self._lock = threading.Lock()
        self._repository_path = repository_path

    def _set_state(self, name: str, state: str, reason: str = "") -> None:
        # lock held by caller
        self._state[name] = state
        self._reason[name] = reason
        if state == STATE_READY:
            self._capacity_failed.discard(name)

    def _classify_failure(self, name: str, capacity: bool) -> None:
        # lock held by caller. Membership must track the LATEST failure:
        # a capacity miss followed by a real load bug (corrupt weights)
        # must degrade, and vice versa.
        if capacity:
            self._capacity_failed.add(name)
        else:
            self._capacity_failed.discard(name)

    def add_model(self, model: Model, ready: bool = True) -> None:
        """Register a programmatic model. A warmup failure does NOT
        raise: the model registers as UNAVAILABLE with reason
        ``load failed: <why>`` — the same index semantics a failed
        directory load gets — so one unloadable model (e.g. a sharded
        model whose mesh needs more devices than the host has) degrades
        to a clean per-model 503 instead of blocking server startup.
        A later programmatic ``load()`` re-runs warmup and recovers it."""
        failure: Optional[str] = None
        capacity = False
        try:
            model.warmup()
        except Exception as e:  # noqa: BLE001 - surfaced via the index
            failure = f"load failed: {e}"
            capacity = _mesh_capacity_failure(e)
        with self._lock:
            self._models[model.name] = model
            if failure is not None:
                self._set_state(model.name, STATE_UNAVAILABLE, failure)
                self._classify_failure(model.name, capacity)
            else:
                self._set_state(
                    model.name, STATE_READY if ready else STATE_UNAVAILABLE
                )
            self._epoch[model.name] = self._epoch.get(model.name, 0) + 1

    def peek(self, name: str) -> Optional[Model]:
        """The registered model object regardless of readiness (the server
        core uses it to pin per-model state across an unload)."""
        with self._lock:
            return self._models.get(name)

    def get(self, name: str, version: str = "") -> Model:
        with self._lock:
            model = self._models.get(name)
            ready = self._state.get(name) == STATE_READY
        if model is None:
            raise InferenceServerException(
                f"Request for unknown model: '{name}' is not found"
            )
        if not ready:
            raise ModelUnavailableError(
                f"Request for unavailable model: '{name}' is not ready"
            )
        if version and version != model.version:
            raise InferenceServerException(
                f"Request for unknown model version: '{name}' version "
                f"{version} is not found"
            )
        return model

    def is_ready(self, name: str, version: str = "") -> bool:
        with self._lock:
            if name not in self._models:
                return False
            if version and self._models[name].version != version:
                return False
            return self._state.get(name) == STATE_READY

    def degraded(self) -> bool:
        """True when the ready set is degraded: a model is mid-load or
        stuck in a failed load. Intentional removals (unloading/unloaded)
        do NOT degrade readiness — draining one model out of a serving
        process is normal operations, not an unhealthy server."""
        with self._lock:
            for name in self._models:
                if self._state.get(name) == STATE_LOADING:
                    return True
                if (
                    self._reason.get(name, "").startswith("load failed")
                    and name not in self._capacity_failed
                ):
                    return True
        return False

    def index(self) -> List[Dict[str, str]]:
        with self._lock:
            return [
                {
                    "name": m.name,
                    "version": m.version,
                    "state": self._state.get(m.name, STATE_UNAVAILABLE),
                    "reason": self._reason.get(m.name, ""),
                }
                for m in self._models.values()
            ]

    def load(self, name: str, config_override: Optional[str] = None) -> None:
        """Load (or reload) a model by name — atomically.

        Directory models are (re-)imported from ``<repo>/<name>/model.py``;
        an already-serving model keeps serving the OLD object until the
        new one passes ``warmup()``, then requests cut over in one swap.
        A failed load leaves the old model serving (the error still
        propagates to the caller). Programmatic models are re-warmed on
        reload — a bare re-mark-ready would resurrect a model that was
        unloaded precisely because its state went bad.
        """
        model_py = (
            os.path.join(self._repository_path, name, "model.py")
            if self._repository_path
            else None
        )
        with self._lock:
            known = name in self._models
            was_ready = self._state.get(name) == STATE_READY
        if model_py is None or not os.path.exists(model_py):
            if not known:
                if self._repository_path is None:
                    raise InferenceServerException(
                        f"failed to load '{name}': no model repository "
                        "configured"
                    )
                raise InferenceServerException(
                    f"failed to load '{name}': {model_py} not found"
                )
            # Programmatic reload: same object, fresh warmup.
            model = self._models[name]
            try:
                model.warmup()
            except Exception as e:  # noqa: BLE001 - surfaced to caller
                with self._lock:
                    if not was_ready:
                        self._set_state(
                            name, STATE_UNAVAILABLE, f"load failed: {e}"
                        )
                        self._classify_failure(
                            name, _mesh_capacity_failure(e)
                        )
                raise InferenceServerException(
                    f"failed to load '{name}': {e}"
                ) from e
            with self._lock:
                self._set_state(name, STATE_READY)
                self._epoch[name] = self._epoch.get(name, 0) + 1
            return
        with self._lock:
            # Old model (if ready) keeps serving through the load; a brand
            # new name is LOADING (not ready) until warmup passes.
            if known and was_ready:
                self._reason[name] = "loading"
            else:
                self._set_state(name, STATE_LOADING, "loading")
        try:
            spec = importlib.util.spec_from_file_location(
                f"client_tpu_model_{name}", model_py
            )
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
            if not hasattr(module, "create_model"):
                raise InferenceServerException(
                    f"failed to load '{name}': model.py must define "
                    "create_model()"
                )
            model = module.create_model()
            if config_override:
                try:
                    overrides = json.loads(config_override)
                except json.JSONDecodeError as e:
                    raise InferenceServerException(
                        f"failed to load '{name}': bad config override: {e}"
                    ) from None
                if "max_batch_size" in overrides:
                    model.max_batch_size = int(overrides["max_batch_size"])
            model.name = name
            model.warmup()
        except Exception as e:  # noqa: BLE001 - load failure bookkeeping
            with self._lock:
                if known and was_ready:
                    # old model still serving: load failure is an event,
                    # not a state — readiness is untouched
                    self._reason[name] = ""
                elif known:
                    self._set_state(
                        name, STATE_UNAVAILABLE, f"load failed: {e}"
                    )
                    self._classify_failure(name, _mesh_capacity_failure(e))
                else:
                    # never-loaded name: no registry entry to degrade
                    self._state.pop(name, None)
                    self._reason.pop(name, None)
            if isinstance(e, InferenceServerException):
                raise
            raise InferenceServerException(
                f"failed to load '{name}': {e}"
            ) from e
        # Atomic cutover: one assignment under the lock; requests admitted
        # before this instant run to completion against the old object.
        with self._lock:
            self._models[name] = model
            self._set_state(name, STATE_READY)
            self._epoch[name] = self._epoch.get(name, 0) + 1

    def unload(self, name: str) -> int:
        """Begin unloading: the model stops admitting immediately (new
        requests get a 503/UNAVAILABLE :class:`ModelUnavailableError`)
        while queued and in-flight work drains. Returns the unload epoch;
        the caller (ServerCore) drains and then calls
        :meth:`finish_unload` with it."""
        with self._lock:
            if name not in self._models:
                raise InferenceServerException(
                    f"failed to unload '{name}': model is not loaded"
                )
            self._set_state(name, STATE_UNLOADING, "unloading")
            self._epoch[name] = self._epoch.get(name, 0) + 1
            return self._epoch[name]

    def epoch_of(self, name: str) -> Optional[int]:
        """The model's current load/unload generation (None if unknown).
        Callers finalizing an async unload compare against the epoch
        :meth:`unload` returned — a mismatch means a load() superseded
        the unload and its cleanup must not touch the new model."""
        with self._lock:
            return self._epoch.get(name)

    def finish_unload(self, name: str, epoch: Optional[int] = None) -> None:
        """Mark an unload complete (state UNAVAILABLE, reason "unloaded").
        With ``epoch``, a no-op when a load() superseded the unload."""
        with self._lock:
            if epoch is not None and self._epoch.get(name) != epoch:
                return
            if self._state.get(name) == STATE_UNLOADING:
                self._set_state(name, STATE_UNAVAILABLE, "unloaded")

    def scan(self) -> None:
        """Load every model directory found in the repository path."""
        if not self._repository_path:
            return
        for entry in sorted(os.listdir(self._repository_path)):
            if os.path.exists(
                os.path.join(self._repository_path, entry, "model.py")
            ):
                self.load(entry)


def build_repository(
    repository_path=None, builtin: bool = True, zoo: bool = False
) -> "ModelRepository":
    """Standard repository bootstrap shared by the CLI server, the
    in-process test server, and the embedded (perf local-backend) runner:
    fixture models, optional model-zoo adapters, then a directory scan."""
    repository = ModelRepository(repository_path)
    if builtin:
        from client_tpu.server.models import register_builtin_models

        register_builtin_models(repository)
    if zoo:
        from client_tpu.models.serving import register_zoo_models

        register_zoo_models(repository)
    repository.scan()
    return repository
