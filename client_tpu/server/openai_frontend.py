"""OpenAI-compatible front-end routes (chat/completions with SSE streaming).

The reference perf_analyzer ships an OpenAI client backend that benchmarks
chat-completions endpoints with SSE token streaming (reference
client_backend/openai/openai_client.h:132-167, http_client SSE handling).
This module provides the server half in this stack so the same benchmark
path is self-contained: requests are tokenized with the deterministic
synthetic tokenizer, driven through a decoupled LLM decode model
(INPUT_IDS -> OUTPUT_IDS, e.g. the JAX llama ``llm_decode`` model), and
streamed back one SSE chunk per generated token.
"""

import json
import time
from typing import Any, Dict, Optional

import numpy as np
from aiohttp import web

from client_tpu.genai_perf.tokenizer import SyntheticTokenizer
from client_tpu.utils import InferenceServerException


def _messages_to_prompt(body: Dict[str, Any]) -> str:
    if "messages" in body:
        return "\n".join(
            str(m.get("content", "")) for m in body.get("messages", [])
        )
    return str(body.get("prompt", ""))


# Hard ceiling for the request-body max_tokens field: far above any model
# this stack serves (max_seq_len <= 4096) but small enough that a client
# typo (e.g. milliseconds pasted into max_tokens) fails fast with a 400
# instead of erroring mid-stream after the SSE 200 is committed.
MAX_TOKENS_CAP = 131072


def _invalid_request(message: str, param: str) -> web.Response:
    """OpenAI-style 400 error body (error.type/param/code, the shape
    OpenAI SDKs surface to callers)."""
    return web.json_response(
        {
            "error": {
                "message": message,
                "type": "invalid_request_error",
                "param": param,
                "code": "invalid_value",
            }
        },
        status=400,
    )


class OpenAiFrontend:
    def __init__(self, core, default_model: str = "llm_decode"):
        self.core = core
        self.default_model = default_model
        self.tokenizer = SyntheticTokenizer()
        self._counter = 0

    def add_routes(self, app: web.Application, guard=None) -> None:
        wrap = guard if guard is not None else (lambda h: h)
        app.router.add_post("/v1/chat/completions", wrap(self.handle_chat))
        app.router.add_post("/v1/completions", wrap(self.handle_chat))
        app.router.add_get("/v1/models", wrap(self.handle_models))

    async def handle_models(self, request: web.Request) -> web.Response:
        # Only READY models are listable: an unloaded/UNAVAILABLE entry
        # in /v1/models would advertise a model whose requests 503 —
        # OpenAI clients treat the listing as "what I can call now".
        from client_tpu.server.model_repository import STATE_READY

        models = [
            {"id": entry["name"], "object": "model", "owned_by": "client_tpu"}
            for entry in self.core.repository.index()
            if entry.get("state") == STATE_READY
        ]
        return web.json_response({"object": "list", "data": models})

    def _decode_stream(self, model_name: str, prompt_ids, max_tokens: int,
                       sampling: Optional[Dict[str, Any]] = None):
        """Async iterator of generated token ids from the decoupled model."""
        from client_tpu.server.core import CoreRequest, CoreTensor

        parameters: Dict[str, Any] = {"max_tokens": max_tokens}
        if sampling:
            parameters.update(sampling)
        request = CoreRequest(
            model_name=model_name,
            model_version="",
            id="",
            inputs=[
                CoreTensor(
                    name="INPUT_IDS",
                    datatype="INT32",
                    shape=[len(prompt_ids)],
                    data=np.asarray(prompt_ids, dtype=np.int32),
                )
            ],
            parameters=parameters,
        )
        return self.core.infer_decoupled(request)

    async def handle_chat(self, request: web.Request) -> web.Response:
        is_chat = request.path.endswith("/chat/completions")
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                {"error": {"message": "invalid JSON body"}}, status=400
            )
        model_name = body.get("model") or self.default_model
        prompt = _messages_to_prompt(body)
        prompt_ids = self.tokenizer.encode(prompt) or [2]
        # Validate max_tokens BEFORE any work: a non-int, non-positive,
        # or absurd value must be a clean 400 with an OpenAI-style error
        # body, never a 500 (or an in-band error after SSE commits).
        raw_max = body.get("max_tokens", None)
        if raw_max is None:
            max_tokens = 16
        else:
            if isinstance(raw_max, bool) or not isinstance(raw_max, int):
                return _invalid_request(
                    f"max_tokens must be an integer, got "
                    f"{type(raw_max).__name__}",
                    "max_tokens",
                )
            if raw_max <= 0:
                return _invalid_request(
                    f"max_tokens must be a positive integer, got {raw_max}",
                    "max_tokens",
                )
            if raw_max > MAX_TOKENS_CAP:
                return _invalid_request(
                    f"max_tokens must be <= {MAX_TOKENS_CAP}, got {raw_max}",
                    "max_tokens",
                )
            max_tokens = raw_max
        # Sampling controls (OpenAI body fields -> engine request
        # parameters): temperature 0 stays greedy; seed makes a sampled
        # generation reproducible (per-token PRNG chain, replayed across
        # engine preemption). Validated here for clean 400s.
        sampling: Dict[str, Any] = {}
        raw_temperature = body.get("temperature", None)
        if raw_temperature is not None:
            if isinstance(raw_temperature, bool) or not isinstance(
                raw_temperature, (int, float)
            ) or raw_temperature < 0:
                return _invalid_request(
                    f"temperature must be a non-negative number, got "
                    f"{raw_temperature!r}",
                    "temperature",
                )
            sampling["temperature"] = float(raw_temperature)
        raw_seed = body.get("seed", None)
        if raw_seed is not None:
            if isinstance(raw_seed, bool) or not isinstance(raw_seed, int):
                return _invalid_request(
                    f"seed must be an integer, got {raw_seed!r}", "seed"
                )
            sampling["seed"] = raw_seed
        raw_top_k = body.get("top_k", None)
        if raw_top_k is not None:
            if isinstance(raw_top_k, bool) or not isinstance(
                raw_top_k, int
            ) or raw_top_k < 0:
                return _invalid_request(
                    f"top_k must be a non-negative integer, got "
                    f"{raw_top_k!r}",
                    "top_k",
                )
            sampling["top_k"] = raw_top_k
        stream = bool(body.get("stream", False))
        self._counter += 1
        completion_id = f"chatcmpl-{self._counter}"
        created = int(time.time())
        object_name = (
            "chat.completion.chunk" if (is_chat and stream)
            else "chat.completion" if is_chat
            else "text_completion"
        )

        def chunk(delta_text, finish):
            choice: Dict[str, Any] = {"index": 0, "finish_reason": finish}
            if is_chat:
                choice["delta"] = (
                    {"content": delta_text} if delta_text is not None else {}
                )
            else:
                choice["text"] = delta_text or ""
            return {
                "id": completion_id,
                "object": object_name,
                "created": created,
                "model": model_name,
                "choices": [choice],
            }

        # Validate the model BEFORE any SSE headers go out: after
        # resp.prepare() the 200 is committed and errors can only be
        # delivered in-band.
        try:
            self.core.repository.get(model_name, "")
        except InferenceServerException as e:
            return web.json_response(
                {"error": {"message": e.message()}}, status=404
            )
        try:
            iterator = self._decode_stream(
                model_name, prompt_ids, max_tokens, sampling
            )
            if stream:
                # Pull the FIRST response before committing the SSE 200:
                # submit-time rejections (context exceeds the model's
                # max_seq_len, queue full) surface as real HTTP errors
                # with their carried status (400/429/...), not in-band
                # events after a 200. The mid-stream escape hatch below
                # still covers failures once tokens are flowing.
                first = None
                try:
                    first = await iterator.__anext__()
                except StopAsyncIteration:
                    iterator = None
                except InferenceServerException as e:
                    return _mapped_error(e)
                resp = web.StreamResponse(
                    headers={
                        "Content-Type": "text/event-stream",
                        "Cache-Control": "no-cache",
                    }
                )
                await resp.prepare(request)
                count = 0
                try:
                    async for core_response in _chain(first, iterator):
                        ids = _output_ids(core_response)
                        if ids is None:
                            continue
                        text = (
                            " " if count else ""
                        ) + self.tokenizer.decode(ids)
                        count += len(ids)
                        await resp.write(
                            b"data: "
                            + json.dumps(chunk(text, None)).encode()
                            + b"\n\n"
                        )
                    await resp.write(
                        b"data: " + json.dumps(chunk(None, "stop")).encode()
                        + b"\n\n"
                    )
                except InferenceServerException as e:
                    # Mid-stream failure: deliver the error in-band, then
                    # terminate the stream cleanly.
                    await resp.write(
                        b"data: "
                        + json.dumps(
                            {"error": {"message": e.message()}}
                        ).encode()
                        + b"\n\n"
                    )
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
                return resp
            pieces = []
            completion_tokens = 0
            async for core_response in iterator:
                ids = _output_ids(core_response)
                if ids is not None:
                    pieces.append(self.tokenizer.decode(ids))
                    completion_tokens += len(ids)
            text = " ".join(pieces)
            doc = chunk(None, "stop")
            if is_chat:
                doc["choices"][0].pop("delta", None)
                doc["choices"][0]["message"] = {
                    "role": "assistant",
                    "content": text,
                }
            else:
                doc["choices"][0]["text"] = text
            # Count token ids, not decoupled responses — a response may carry
            # several ids (streaming path counts the same way).
            doc["usage"] = {
                "prompt_tokens": len(prompt_ids),
                "completion_tokens": completion_tokens,
                "total_tokens": len(prompt_ids) + completion_tokens,
            }
            return web.json_response(doc)
        except InferenceServerException as e:
            return _mapped_error(e)


def _mapped_error(e: InferenceServerException) -> web.Response:
    """Error response in the OpenAI body shape but with the exception's
    carried wire face (429/504/...), including the Retry-After hint the
    resilience layer honors — mirroring http_server._map_exception."""
    headers = None
    retry_after_s = getattr(e, "retry_after_s", None)
    if retry_after_s:
        headers = {"Retry-After": str(max(1, int(round(retry_after_s))))}
    return web.json_response(
        {"error": {"message": e.message()}},
        status=getattr(e, "http_status", None) or 400,
        headers=headers,
    )


async def _chain(first, rest):
    """Re-attach a prefetched first response to the remaining stream."""
    if first is not None:
        yield first
    if rest is not None:
        async for response in rest:
            yield response


def _output_ids(core_response):
    for tensor in core_response.outputs:
        if tensor.name in ("OUTPUT_IDS", "OUT"):
            return np.asarray(tensor.data).reshape(-1).tolist()
    return None
