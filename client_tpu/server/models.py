"""Built-in JAX models for the in-repo server.

These mirror the fixture models the reference test/bench flows rely on:
``simple`` (the add_sub model every quick-start and integration test uses,
reference src/c++/tests/cc_client_test.cc), ``identity`` variants (BYTES and
fixed-size passthrough), and a decoupled ``repeat`` model for token-streaming
paths (reference custom_repeat example) — implemented as jitted JAX
functions, not torch/CUDA.
"""

import asyncio
from typing import Any, AsyncIterator, Dict, List

import numpy as np

from client_tpu.server.model_repository import Model
from client_tpu.utils import InferenceServerException


def pad_batch_bucket(rows: int, minimum: int = 1) -> int:
    """Next power-of-two batch bucket — bounds XLA retraces under dynamic
    batching to O(log max_batch) compiled programs."""
    bucket = max(minimum, 1)
    while bucket < rows:
        bucket *= 2
    return bucket


def run_bucketed(fn, *arrays):
    """Zero-pad the leading (batch) dim of every array to a shared
    power-of-two bucket, call ``fn(*padded)``, read ALL outputs back with
    ONE batched transfer, and slice back to the true batch size.

    Per-array readbacks cost ~tens of ms each through a TPU relay
    (PERF.md); the bucket bounds XLA retraces to O(log max_batch).
    ``fn`` must return a tuple/list of arrays batched on the leading dim.
    """
    import jax

    rows = arrays[0].shape[0]
    bucket = pad_batch_bucket(rows)
    if bucket != rows:
        arrays = tuple(
            np.concatenate(
                [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)]
            )
            for a in arrays
        )
    outputs = jax.device_get(fn(*arrays))
    return tuple(np.asarray(o)[:rows] for o in outputs)


class AddSubModel(Model):
    """The canonical 'simple' model: OUTPUT0=IN0+IN1, OUTPUT1=IN0-IN1.

    INT32 [1,16] like the reference quick-start model (perf baselines in
    BASELINE.md target this model's request path).
    """

    platform = "jax"
    backend = "jax"
    max_batch_size = 64
    inputs = [
        {"name": "INPUT0", "datatype": "INT32", "shape": [16]},
        {"name": "INPUT1", "datatype": "INT32", "shape": [16]},
    ]
    outputs = [
        {"name": "OUTPUT0", "datatype": "INT32", "shape": [16]},
        {"name": "OUTPUT1", "datatype": "INT32", "shape": [16]},
    ]

    # Device placement: the reference's quick-start 'simple' config is a
    # host model (BASELINE.json configs: "'simple' add_sub model (CPU, no
    # shm)"), and on TPU relays a device round-trip costs a flat ~67 ms per
    # readback vs ~55 µs on the host JAX backend (measured; PERF.md) — tiny
    # elementwise models belong on host, accelerator models (resnet, llama)
    # on TPU.
    device = "cpu"

    def __init__(self, name: str = "simple"):
        self.name = name
        self._fn = None

    def warmup(self) -> None:
        import jax

        @jax.jit
        def add_sub(a, b):
            return a + b, a - b

        self._fn = add_sub
        # Compile the batch-1 bucket so the first request is fast; other
        # power-of-two buckets compile on first use and are cached.
        z = np.zeros([1, 16], dtype=np.int32)
        with self.placement():
            jax.block_until_ready(self._fn(z, z))

    def execute(self, inputs, parameters):
        a, b = inputs.get("INPUT0"), inputs.get("INPUT1")
        if a is None or b is None:
            raise InferenceServerException(
                "model 'simple' expects inputs INPUT0 and INPUT1"
            )
        if a.shape != b.shape:
            raise InferenceServerException(
                f"INPUT0 shape {list(a.shape)} != INPUT1 shape {list(b.shape)}"
            )
        out0, out1 = run_bucketed(self._fn, a, b)
        return {"OUTPUT0": out0, "OUTPUT1": out1}


class IdentityModel(Model):
    """Fixed-dtype passthrough (any shape): OUTPUT0 = INPUT0."""

    max_batch_size = 0

    def __init__(self, name: str = "identity_fp32", datatype: str = "FP32"):
        self.name = name
        self._datatype = datatype
        self.inputs = [{"name": "INPUT0", "datatype": datatype, "shape": [-1]}]
        self.outputs = [{"name": "OUTPUT0", "datatype": datatype, "shape": [-1]}]

    def execute(self, inputs, parameters):
        if "INPUT0" not in inputs:
            raise InferenceServerException(
                f"model '{self.name}' expects input INPUT0"
            )
        return {"OUTPUT0": inputs["INPUT0"]}


class BytesIdentityModel(IdentityModel):
    """BYTES passthrough — exercises string-tensor serialization."""

    def __init__(self, name: str = "identity_bytes"):
        super().__init__(name=name, datatype="BYTES")


class RepeatModel(Model):
    """Decoupled model: streams IN[i] back as one response per element.

    The minimal stand-in for token-by-token LLM decode streaming (reference
    decoupled custom_repeat example; token streaming contract SURVEY.md §5
    long-context notes). Honors a ``delay_us`` parameter between responses.
    """

    decoupled = True
    max_batch_size = 0
    inputs = [{"name": "IN", "datatype": "INT32", "shape": [-1]}]
    outputs = [{"name": "OUT", "datatype": "INT32", "shape": [1]}]

    def __init__(self, name: str = "repeat_int32"):
        self.name = name

    async def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> AsyncIterator[Dict[str, np.ndarray]]:
        if "IN" not in inputs:
            raise InferenceServerException("model 'repeat' expects input IN")
        delay_us = int(parameters.get("delay_us", 0))
        values = inputs["IN"].reshape(-1)
        for i, v in enumerate(values):
            if delay_us:
                await asyncio.sleep(delay_us / 1e6)
            yield {
                "OUT": np.array([v], dtype=np.int32),
                "__final__": i == len(values) - 1,
            }


def register_builtin_models(repository) -> None:
    """Install the fixture models into a repository."""
    repository.add_model(AddSubModel())
    repository.add_model(IdentityModel("identity_fp32", "FP32"))
    repository.add_model(IdentityModel("identity_bf16", "BF16"))
    repository.add_model(BytesIdentityModel())
    repository.add_model(RepeatModel())
