"""Built-in JAX models for the in-repo server.

These mirror the fixture models the reference test/bench flows rely on:
``simple`` (the add_sub model every quick-start and integration test uses,
reference src/c++/tests/cc_client_test.cc), ``identity`` variants (BYTES and
fixed-size passthrough), and a decoupled ``repeat`` model for token-streaming
paths (reference custom_repeat example) — implemented as jitted JAX
functions, not torch/CUDA.
"""

import asyncio
from typing import Any, AsyncIterator, Dict

import numpy as np

from client_tpu.server.model_repository import Model
from client_tpu.utils import InferenceServerException


def pad_batch_bucket(rows: int, minimum: int = 1) -> int:
    """Next power-of-two batch bucket — bounds XLA retraces under dynamic
    batching to O(log max_batch) compiled programs."""
    bucket = max(minimum, 1)
    while bucket < rows:
        bucket *= 2
    return bucket


def run_bucketed(fn, *arrays):
    """Zero-pad the leading (batch) dim of every array to a shared
    power-of-two bucket, call ``fn(*padded)``, read ALL outputs back with
    ONE batched transfer, and slice back to the true batch size.

    Per-array readbacks cost ~tens of ms each through a TPU relay
    (PERF.md); the bucket bounds XLA retraces to O(log max_batch).
    ``fn`` must return a tuple/list of arrays batched on the leading dim.
    """
    import jax

    rows = arrays[0].shape[0]
    bucket = pad_batch_bucket(rows)
    if bucket != rows:
        arrays = tuple(
            np.concatenate(
                [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)]
            )
            for a in arrays
        )
    outputs = jax.device_get(fn(*arrays))
    return tuple(np.asarray(o)[:rows] for o in outputs)


class AddSubModel(Model):
    """The canonical 'simple' model: OUTPUT0=IN0+IN1, OUTPUT1=IN0-IN1.

    INT32 [1,16] like the reference quick-start model (perf baselines in
    BASELINE.md target this model's request path).
    """

    platform = "jax"
    backend = "jax"
    max_batch_size = 64
    inputs = [
        {"name": "INPUT0", "datatype": "INT32", "shape": [16]},
        {"name": "INPUT1", "datatype": "INT32", "shape": [16]},
    ]
    outputs = [
        {"name": "OUTPUT0", "datatype": "INT32", "shape": [16]},
        {"name": "OUTPUT1", "datatype": "INT32", "shape": [16]},
    ]

    # Device placement: the reference's quick-start 'simple' config is a
    # host model (BASELINE.json configs: "'simple' add_sub model (CPU, no
    # shm)"), and on TPU relays a device round-trip costs a flat ~67 ms per
    # readback vs ~55 µs on the host JAX backend (measured; PERF.md) — tiny
    # elementwise models belong on host, accelerator models (resnet, llama)
    # on TPU.
    device = "cpu"

    def __init__(self, name: str = "simple"):
        self.name = name
        self._fn = None

    def warmup(self) -> None:
        import jax

        @jax.jit
        def add_sub(a, b):
            return a + b, a - b

        self._fn = add_sub
        # Compile the batch-1 bucket so the first request is fast; other
        # power-of-two buckets compile on first use and are cached.
        z = np.zeros([1, 16], dtype=np.int32)
        with self.placement():
            jax.block_until_ready(self._fn(z, z))

    def execute(self, inputs, parameters):
        a, b = inputs.get("INPUT0"), inputs.get("INPUT1")
        if a is None or b is None:
            raise InferenceServerException(
                "model 'simple' expects inputs INPUT0 and INPUT1"
            )
        if a.shape != b.shape:
            raise InferenceServerException(
                f"INPUT0 shape {list(a.shape)} != INPUT1 shape {list(b.shape)}"
            )
        out0, out1 = run_bucketed(self._fn, a, b)
        return {"OUTPUT0": out0, "OUTPUT1": out1}


class IdentityModel(Model):
    """Fixed-dtype passthrough (any shape): OUTPUT0 = INPUT0."""

    max_batch_size = 0

    def __init__(self, name: str = "identity_fp32", datatype: str = "FP32"):
        self.name = name
        self._datatype = datatype
        self.inputs = [{"name": "INPUT0", "datatype": datatype, "shape": [-1]}]
        self.outputs = [{"name": "OUTPUT0", "datatype": datatype, "shape": [-1]}]

    def execute(self, inputs, parameters):
        if "INPUT0" not in inputs:
            raise InferenceServerException(
                f"model '{self.name}' expects input INPUT0"
            )
        # Execution-delay knob for timeout/deadline tests (the role of the
        # reference identity backend's execute_delay parameter): requests
        # carrying delay_ms sleep that long before responding.
        delay_ms = parameters.get("delay_ms") if parameters else None
        if delay_ms:
            import time as _time

            _time.sleep(min(float(delay_ms), 10_000) / 1000.0)
        return {"OUTPUT0": inputs["INPUT0"]}


class BytesIdentityModel(IdentityModel):
    """BYTES passthrough — exercises string-tensor serialization."""

    def __init__(self, name: str = "identity_bytes"):
        super().__init__(name=name, datatype="BYTES")


class RepeatModel(Model):
    """Decoupled model: streams IN[i] back as one response per element.

    The minimal stand-in for token-by-token LLM decode streaming (reference
    decoupled custom_repeat example; token streaming contract SURVEY.md §5
    long-context notes). Honors a ``delay_us`` parameter between responses.
    """

    decoupled = True
    max_batch_size = 0
    inputs = [{"name": "IN", "datatype": "INT32", "shape": [-1]}]
    outputs = [{"name": "OUT", "datatype": "INT32", "shape": [1]}]

    def __init__(self, name: str = "repeat_int32"):
        self.name = name

    async def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> AsyncIterator[Dict[str, np.ndarray]]:
        if "IN" not in inputs:
            raise InferenceServerException("model 'repeat' expects input IN")
        delay_us = int(parameters.get("delay_us", 0))
        values = inputs["IN"].reshape(-1)
        for i, v in enumerate(values):
            if delay_us:
                await asyncio.sleep(delay_us / 1e6)
            yield {
                "OUT": np.array([v], dtype=np.int32),
                "__final__": i == len(values) - 1,
            }


class SequenceAccumulatorModel(Model):
    """Stateful sequence model: OUTPUT = running sum of INPUT per sequence.

    Declares ``sequence_batching`` in its config so clients auto-detect the
    scheduler kind (reference model_parser.cc sequence detection; the
    perf harness then drives it with sequence_id/start/end control
    parameters instead of needing a --sequence-model flag). State is keyed
    by the request's ``sequence_id`` parameter; ``sequence_start`` resets,
    ``sequence_end`` evicts.
    """

    max_batch_size = 0
    sequence_batching: Dict[str, Any] = {}
    inputs = [{"name": "INPUT", "datatype": "INT32", "shape": [1]}]
    outputs = [{"name": "OUTPUT", "datatype": "INT32", "shape": [1]}]

    def __init__(self, name: str = "sequence_accumulate"):
        import threading

        self.name = name
        self._totals: Dict[int, int] = {}
        self._lock = threading.Lock()

    def execute(self, inputs, parameters):
        if "INPUT" not in inputs:
            raise InferenceServerException(
                f"model '{self.name}' expects input INPUT"
            )
        seq_id = int(parameters.get("sequence_id", 0))
        if seq_id == 0:
            raise InferenceServerException(
                f"model '{self.name}' is a sequence model; requests need a "
                "non-zero sequence_id"
            )
        value = int(np.asarray(inputs["INPUT"]).reshape(-1)[0])
        with self._lock:
            if parameters.get("sequence_start"):
                self._totals[seq_id] = 0
            if seq_id not in self._totals:
                raise InferenceServerException(
                    f"sequence {seq_id} has no open state; send "
                    "sequence_start first"
                )
            # int32 wraparound semantics: load generators feed arbitrary
            # int32 values, and a running sum must not overflow numpy's
            # bounds checking.
            self._totals[seq_id] = (self._totals[seq_id] + value) & 0xFFFFFFFF
            total = self._totals[seq_id]
            if parameters.get("sequence_end"):
                del self._totals[seq_id]
        return {
            "OUTPUT": np.array([total], dtype=np.uint32).astype(np.int32)
        }


class EnsembleModel(Model):
    """Composes other models into a pipeline (Triton ensembles).

    The config declares ``ensemble_scheduling.step`` entries with Triton's
    semantics: each step's ``input_map`` maps the composing model's input
    name to an ensemble-scope tensor name, ``output_map`` maps its outputs
    into ensemble scope. Steps execute in order inside ONE server-side
    execution — intermediate tensors never touch the wire (the reason
    ensembles exist; reference docs architecture.md ensemble section).
    """

    platform = "ensemble"
    backend = "ensemble"

    def __init__(self, name, repository, inputs, outputs, steps,
                 max_batch_size: int = 0):
        self.name = name
        self._repository = repository
        self.inputs = inputs
        self.outputs = outputs
        self.max_batch_size = max_batch_size
        self._steps = steps
        self.ensemble_scheduling = {"step": steps}

    def warmup(self) -> None:
        produced = {i["name"] for i in self.inputs}
        for step in self._steps:
            model = self._repository.get(step["model_name"])
            if model.decoupled:
                raise InferenceServerException(
                    f"ensemble '{self.name}' cannot compose decoupled "
                    f"model '{model.name}'"
                )
            produced.update(step["output_map"].values())
        # Output coverage is statically checkable: every declared ensemble
        # output must be produced by some step (or be a passthrough input).
        for out in self.outputs:
            if out["name"] not in produced:
                raise InferenceServerException(
                    f"ensemble '{self.name}' declares output "
                    f"'{out['name']}' but no step's output_map produces it"
                )

    def execute(self, inputs, parameters):
        pool = dict(inputs)
        for step in self._steps:
            model = self._repository.get(step["model_name"])
            sub_inputs = {}
            for comp_name, ens_name in step["input_map"].items():
                if ens_name not in pool:
                    raise InferenceServerException(
                        f"ensemble '{self.name}' step "
                        f"'{step['model_name']}' needs tensor '{ens_name}' "
                        "which no prior step produced"
                    )
                sub_inputs[comp_name] = pool[ens_name]
            with model.placement():
                # Request parameters flow to every composing model
                # (sequence controls, sampling knobs, ...), matching the
                # core's behavior on non-ensemble paths.
                raw = model.execute(sub_inputs, parameters)
            for comp_name, ens_name in step["output_map"].items():
                if comp_name not in raw:
                    raise InferenceServerException(
                        f"composing model '{step['model_name']}' produced "
                        f"no output '{comp_name}'"
                    )
                pool[ens_name] = raw[comp_name]
        missing = [o["name"] for o in self.outputs if o["name"] not in pool]
        if missing:
            raise InferenceServerException(
                f"ensemble '{self.name}' produced no tensor for declared "
                f"outputs {missing}"
            )
        return {o["name"]: pool[o["name"]] for o in self.outputs}


def register_builtin_models(repository) -> None:
    """Install the fixture models into a repository."""
    repository.add_model(AddSubModel())
    repository.add_model(IdentityModel("identity_fp32", "FP32"))
    repository.add_model(IdentityModel("identity_bf16", "BF16"))
    repository.add_model(BytesIdentityModel())
    repository.add_model(RepeatModel())
    repository.add_model(SequenceAccumulatorModel())
    # Demo ensemble: simple -> simple. OUTPUT0 = 2*INPUT0, OUTPUT1 =
    # 2*INPUT1 ((a+b)+(a-b), (a+b)-(a-b)).
    repository.add_model(
        EnsembleModel(
            "add_sub_chain",
            repository,
            inputs=[
                {"name": "INPUT0", "datatype": "INT32", "shape": [16]},
                {"name": "INPUT1", "datatype": "INT32", "shape": [16]},
            ],
            outputs=[
                {"name": "OUTPUT0", "datatype": "INT32", "shape": [16]},
                {"name": "OUTPUT1", "datatype": "INT32", "shape": [16]},
            ],
            steps=[
                {
                    "model_name": "simple",
                    "input_map": {"INPUT0": "INPUT0", "INPUT1": "INPUT1"},
                    "output_map": {"OUTPUT0": "mid0", "OUTPUT1": "mid1"},
                },
                {
                    "model_name": "simple",
                    "input_map": {"INPUT0": "mid0", "INPUT1": "mid1"},
                    "output_map": {
                        "OUTPUT0": "OUTPUT0",
                        "OUTPUT1": "OUTPUT1",
                    },
                },
            ],
            max_batch_size=64,
        )
    )
