"""KServe v2 HTTP/REST front-end (aiohttp) over :class:`ServerCore`.

Implements the endpoint surface the client stack exercises: health,
metadata, config, repository control, statistics, trace/log settings,
system/CUDA/TPU shared-memory registration, and binary-tensor inference
(JSON header + concatenated raw buffers, ``Inference-Header-Content-Length``).
"""

import asyncio
import base64
import gzip
import json
import zlib
from typing import Any, Dict, List, Optional

import numpy as np
from aiohttp import web

from client_tpu.observability import TRACEPARENT_HEADER
from client_tpu.server import shm_ring

# Back-compat alias: /metrics label escaping lived here before the
# registry (client_tpu.observability.metrics) owned the exposition format.
from client_tpu.observability.metrics import (
    escape_label_value as prometheus_escape,  # noqa: F401
)
from client_tpu.server.core import (
    SERVER_EXTENSIONS,
    SERVER_NAME,
    SERVER_VERSION,
    CoreRequest,
    CoreRequestedOutput,
    ServerCore,
)
from client_tpu.utils import (
    InferenceServerException,
    serialize_byte_tensor,
)

HEADER_CONTENT_LENGTH = "Inference-Header-Content-Length"


def _error_response(
    msg: str, status: int = 400, headers: Optional[Dict[str, str]] = None
) -> web.Response:
    return web.json_response({"error": msg}, status=status, headers=headers)


def _map_exception(e: InferenceServerException) -> web.Response:
    """InferenceServerException -> HTTP error response. Admission-control
    rejections (client_tpu.scheduling) carry their own wire face:
    queue-full -> 429 with a Retry-After hint (the resilience layer
    classifies 429 as retryable-with-backoff and honors the hint),
    queue timeout -> 504; everything else keeps the historical 400."""
    status = getattr(e, "http_status", None) or 400
    headers = None
    retry_after_s = getattr(e, "retry_after_s", None)
    if retry_after_s:
        headers = {"Retry-After": str(max(1, int(round(retry_after_s))))}
    return _error_response(e.message(), status=status, headers=headers)


def _chaos_middleware(chaos):
    """Fault-injection middleware over a ChaosPolicy: injected latency,
    in-band errors (503), connection resets, and truncated bodies — the
    failure modes a client sees from preempted/restarting TPU hosts."""

    @web.middleware
    async def middleware(request: web.Request, handler):
        if not chaos.applies_to(request.path):
            return await handler(request)
        if chaos.latency_s:
            await asyncio.sleep(chaos.latency_s)
        fate = chaos.draw()
        if fate == "error":
            chaos.record("error")
            return _error_response(
                "chaos: injected unavailability", status=chaos.http_status
            )
        if fate == "reset":
            if request.transport is not None:
                chaos.record("reset")
                request.transport.abort()
                # the connection is gone; this response is never written
                return web.Response(status=500)
            # peer already gone: the fault did not fire, don't count it
            return await handler(request)
        if fate == "truncate":
            response = await handler(request)
            body = bytes(response.body or b"")
            if len(body) >= 2 and request.transport is not None:
                # declare the full length, write half, kill the socket
                chaos.record("truncate")
                truncated = web.StreamResponse(
                    status=response.status, headers=response.headers
                )
                truncated.content_length = len(body)
                await truncated.prepare(request)
                await truncated.write(body[: len(body) // 2])
                request.transport.abort()
                return truncated
            # nothing to truncate: the fault did not fire, don't count it
            return response
        return await handler(request)

    return middleware


def _guarded(handler, logger=None):
    async def wrapper(request: web.Request) -> web.Response:
        try:
            return await handler(request)
        except InferenceServerException as e:
            return _map_exception(e)
        except web.HTTPException:
            raise
        except Exception as e:  # noqa: BLE001 - surface as server error
            if logger is not None:
                # a 500 previously left no server-side trace at all
                logger.error(
                    "internal_error",
                    exc=e,
                    rate_key=("internal_error", request.path),
                    path=request.path,
                    protocol="http",
                )
            return _error_response(f"internal error: {e}", status=500)

    return wrapper


class HttpServer:
    """aiohttp application exposing a ServerCore."""

    def __init__(self, core: ServerCore, chaos=None):
        self.core = core
        middlewares = [_chaos_middleware(chaos)] if chaos is not None else []
        self.app = web.Application(
            client_max_size=1 << 30, middlewares=middlewares
        )
        # one sampling run at a time (a second concurrent /v2/debug/profile
        # gets 409 instead of doubling the sampling overhead)
        self._profiling_busy = False
        self._add_routes()

    def _add_routes(self) -> None:
        r = self.app.router

        def guard(handler, _logger=self.core.logger):
            # every registration below wraps through this: exceptions map
            # to wire errors and internal 500s get a structured record
            return _guarded(handler, _logger)

        g, p = r.add_get, r.add_post
        g("/v2/health/live", guard(self.handle_live))
        g("/v2/health/ready", guard(self.handle_ready))
        g("/v2/models/{model}/ready", guard(self.handle_model_ready))
        g(
            "/v2/models/{model}/versions/{version}/ready",
            guard(self.handle_model_ready),
        )
        g("/v2", guard(self.handle_server_metadata))
        g("/v2/", guard(self.handle_server_metadata))
        g("/v2/models/stats", guard(self.handle_stats))
        g("/v2/models/{model}/stats", guard(self.handle_stats))
        g("/v2/models/{model}/versions/{version}/stats", guard(self.handle_stats))
        g("/v2/models/{model}", guard(self.handle_model_metadata))
        g(
            "/v2/models/{model}/versions/{version}",
            guard(self.handle_model_metadata),
        )
        g("/v2/models/{model}/config", guard(self.handle_model_config))
        g(
            "/v2/models/{model}/versions/{version}/config",
            guard(self.handle_model_config),
        )
        p("/v2/repository/index", guard(self.handle_repository_index))
        p(
            "/v2/repository/models/{model}/load",
            guard(self.handle_repository_load),
        )
        p(
            "/v2/repository/models/{model}/unload",
            guard(self.handle_repository_unload),
        )
        p("/v2/models/{model}/infer", guard(self.handle_infer))
        p(
            "/v2/models/{model}/versions/{version}/infer",
            guard(self.handle_infer),
        )
        for kind in ("system", "cuda", "tpu"):
            base = f"/v2/{kind}sharedmemory"
            g(f"{base}/status", guard(self._shm_status_handler(kind)))
            g(
                f"{base}/region/{{name}}/status",
                guard(self._shm_status_handler(kind)),
            )
            p(
                f"{base}/region/{{name}}/register",
                guard(self._shm_register_handler(kind)),
            )
            p(f"{base}/unregister", guard(self._shm_unregister_handler(kind)))
            p(
                f"{base}/region/{{name}}/unregister",
                guard(self._shm_unregister_handler(kind)),
            )
        g("/v2/trace/setting", guard(self.handle_get_trace))
        p("/v2/trace/setting", guard(self.handle_update_trace))
        g("/v2/models/{model}/trace/setting", guard(self.handle_get_trace))
        p("/v2/models/{model}/trace/setting", guard(self.handle_update_trace))
        g("/v2/logging", guard(self.handle_get_logging))
        p("/v2/logging", guard(self.handle_update_logging))
        g("/v2/models/{model}/logging", guard(self.handle_get_logging))
        p("/v2/models/{model}/logging", guard(self.handle_update_logging))
        # Flight recorder + live-state introspection (the debugging
        # surface: "what are your slowest/failed requests right now?").
        g("/v2/debug/requests", guard(self.handle_debug_requests))
        g("/v2/debug/state", guard(self.handle_debug_state))
        g("/v2/debug/slo", guard(self.handle_debug_slo))
        g("/metrics", guard(self.handle_metrics))
        # Hot-path profiling (observability.profiling): stage-CPU
        # accounting toggle + the on-demand wall-stack sampler.
        g("/v2/debug/profiling", guard(self.handle_get_profiling))
        p("/v2/debug/profiling", guard(self.handle_update_profiling))
        g("/v2/debug/profile", guard(self.handle_profile))
        # OpenAI-compatible front-end (chat/completions + SSE streaming).
        from client_tpu.server.openai_frontend import OpenAiFrontend

        OpenAiFrontend(self.core).add_routes(self.app, guard)
        # TFS + TorchServe REST compatibility (perf-harness backends).
        from client_tpu.server.compat_frontends import CompatFrontends

        CompatFrontends(self.core).add_routes(self.app, guard)

    # -- health / metadata ---------------------------------------------------

    async def handle_live(self, request):
        # Liveness is process health only — it deliberately stays true
        # through a drain so orchestrators don't kill a draining server.
        return web.Response(status=200 if self.core.live else 400)

    async def handle_ready(self, request):
        # Readiness requires live AND accepting (not draining) AND the
        # repository's ready set non-degraded; 503 is what pulls a
        # draining instance out of a load balancer while /live stays 200.
        if self.core.ready:
            return web.Response(status=200)
        headers = None
        if self.core.live and not self.core.lifecycle.accepting:
            retry_after = self.core.lifecycle.retry_after_s
            headers = {"Retry-After": str(max(1, int(round(retry_after))))}
        return web.Response(status=503, headers=headers)

    async def handle_model_ready(self, request):
        ready = self.core.repository.is_ready(
            request.match_info["model"], request.match_info.get("version", "")
        )
        return web.Response(status=200 if ready else 400)

    async def handle_server_metadata(self, request):
        return web.json_response(
            {
                "name": SERVER_NAME,
                "version": SERVER_VERSION,
                "extensions": SERVER_EXTENSIONS,
                # device/mesh topology (the "sharding" extension): host
                # platform + device inventory and every loaded model's
                # mesh occupancy (gRPC clients read the same document
                # from the model config's "mesh" parameter)
                "devices": self.core.device_topology(),
            }
        )

    async def handle_model_metadata(self, request):
        model = self.core.repository.get(
            request.match_info["model"], request.match_info.get("version", "")
        )
        return web.json_response(model.metadata())

    async def handle_model_config(self, request):
        model = self.core.repository.get(
            request.match_info["model"], request.match_info.get("version", "")
        )
        return web.json_response(model.config())

    # -- repository ----------------------------------------------------------

    async def handle_repository_index(self, request):
        return web.json_response(self.core.repository.index())

    async def handle_repository_load(self, request):
        body = await request.read()
        config_override = None
        if body:
            payload = json.loads(body)
            params = payload.get("parameters", {})
            config_override = params.get("config")
        self.core.load_model(
            request.match_info["model"], config_override=config_override
        )
        return web.Response(status=200)

    async def handle_repository_unload(self, request):
        # Through the core, not the bare repository: the model stops
        # admitting immediately while its queued/in-flight work drains in
        # the background, then batcher state is evicted and the index
        # entry flips to UNAVAILABLE/"unloaded".
        self.core.unload_model(request.match_info["model"])
        return web.Response(status=200)

    # -- statistics ----------------------------------------------------------

    async def handle_stats(self, request):
        # "rpc" profiling stage (same booking the gRPC faces make in
        # _grpc_codec.handle_method): the statistics snapshots the perf
        # harness takes per window are part of the server's CPU bill
        from client_tpu.observability.profiling import stage_scope

        with stage_scope(self.core.profiling, "rpc"):
            return web.json_response(
                self.core.statistics(
                    request.match_info.get("model", ""),
                    request.match_info.get("version", ""),
                )
            )

    async def handle_metrics(self, request):
        """Prometheus text metrics, rendered from the core's registry
        (:mod:`client_tpu.server.metrics` — the TPU replacement for the
        reference's nv_* families scraped by perf_analyzer's
        MetricsManager, reference metrics_manager.h:45-92). The registry's
        collect hook takes exactly one statistics snapshot per scrape and
        derives duty cycle from the core's monotone busy-ns counter, so
        concurrent scrapers never corrupt each other's deltas. Render
        CPU books under the "rpc" profiling stage (like the gRPC faces'
        non-inference methods): with --profile-server the harness's own
        scrape cost shows in the attribution instead of hiding.

        ``?exemplars=true`` appends OpenMetrics exemplars (trace id +
        latency) to duration-histogram bucket samples, linking a bucket
        to its ``/v2/debug/requests`` evidence; the default output is
        byte-identical to before the flag existed."""
        from client_tpu.observability.profiling import stage_scope

        exemplars = request.query.get("exemplars", "").lower() in (
            "1", "true", "yes",
        )
        with stage_scope(self.core.profiling, "rpc"):
            return web.Response(
                text=self.core.metrics.render(exemplars=exemplars),
                content_type="text/plain",
            )

    # -- shared memory -------------------------------------------------------

    def _shm_status_handler(self, kind):
        async def handler(request):
            name = request.match_info.get("name", "")
            if kind == "cuda":
                regions: Dict[str, Any] = {}
            else:
                regions = self.core.shm.status(kind, name)
            # HTTP status returns a list of region dicts (Triton wire shape)
            return web.json_response(list(regions.values()))

        return handler

    def _shm_register_handler(self, kind):
        async def handler(request):
            name = request.match_info["name"]
            payload = json.loads(await request.read())
            if kind == "system":
                self.core.shm.register_system(
                    name,
                    payload["key"],
                    int(payload.get("offset", 0)),
                    int(payload["byte_size"]),
                )
            elif kind == "tpu":
                raw_handle = base64.b64decode(payload["raw_handle"]["b64"])
                self.core.shm.register_tpu(
                    name,
                    raw_handle,
                    int(payload.get("device_id", 0)),
                    int(payload["byte_size"]),
                )
            else:
                raise InferenceServerException(
                    "this server has no CUDA devices; use TPU or system "
                    "shared memory"
                )
            return web.Response(status=200)

        return handler

    def _shm_unregister_handler(self, kind):
        async def handler(request):
            name = request.match_info.get("name", "")
            shm_kind = kind if kind != "cuda" else "cuda"
            if name:
                self.core.shm.unregister(name, kind=shm_kind)
            else:
                self.core.shm.unregister_all(kind=shm_kind)
            return web.Response(status=200)

        return handler

    # -- trace / logging -----------------------------------------------------

    @staticmethod
    def _parse_settings_body(body: bytes) -> Dict[str, Any]:
        if not body:
            return {}
        try:
            updates = json.loads(body)
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise InferenceServerException(
                f"malformed settings request: {e}"
            ) from None
        if not isinstance(updates, dict):
            raise InferenceServerException(
                "settings request must be a JSON object"
            )
        return updates

    async def handle_get_trace(self, request):
        model = request.match_info.get("model", "")
        return web.json_response(self.core.trace_manager.settings(model))

    async def handle_update_trace(self, request):
        # Unknown keys and wrong-typed values are rejected with a 400 +
        # JSON error body (Triton behavior) — the manager validates the
        # whole update before applying any of it. A null value clears a
        # per-model override / resets a global setting.
        updates = self._parse_settings_body(await request.read())
        model = request.match_info.get("model", "")
        return web.json_response(
            self.core.trace_manager.update(updates, model)
        )

    async def handle_get_logging(self, request):
        model = request.match_info.get("model", "")
        return web.json_response(self.core.logger.settings(model))

    async def handle_update_logging(self, request):
        # Backed by the real structured logger: applying an update
        # changes what the server emits immediately (no restart). The
        # model scope comes from the /v2/models/{model}/logging route or
        # a "model" key in the body (the gRPC wire uses the same key); a
        # null value clears a per-model override / resets a global
        # setting, mirroring the trace-settings RPC.
        updates = self._parse_settings_body(await request.read())
        model = request.match_info.get("model", "")
        body_model = updates.pop("model", None)
        if body_model is not None:
            if not isinstance(body_model, str):
                raise InferenceServerException(
                    f"log setting 'model' expects a string, got {body_model!r}"
                )
            model = body_model
        return web.json_response(self.core.update_log_settings(updates, model))

    # -- flight recorder / live state ----------------------------------------

    async def handle_debug_requests(self, request):
        """Recent / failed / slowest request exemplars
        (``?model=`` filter, ``?limit=`` per-section cap)."""
        model = request.query.get("model") or None
        limit = request.query.get("limit")
        if limit is not None:
            try:
                limit = int(limit)
            except ValueError:
                raise InferenceServerException(
                    f"debug requests limit must be an integer, got '{limit}'"
                ) from None
        return web.json_response(
            self.core.flight_recorder.snapshot(model=model, limit=limit)
        )

    async def handle_debug_state(self, request):
        return web.json_response(self.core.debug_state())

    async def handle_debug_slo(self, request):
        """Live telemetry document: rolling 30s/5m latency quantiles per
        model plus SLO error-budget burn for models declaring one —
        the "what is p99 RIGHT NOW" answer the cumulative statistics
        extension cannot give."""
        return web.json_response(self.core.debug_slo())

    # -- profiling -----------------------------------------------------------

    async def handle_get_profiling(self, request):
        # enabled flag + calibration outcome (clock mode, sample stride)
        return web.json_response(self.core.profiling.config())

    async def handle_update_profiling(self, request):
        """Toggle per-stage thread-CPU accounting (default off). The perf
        harness's ``--profile-server`` flips it on for the run's duration
        and restores the previous setting afterwards."""
        updates = self._parse_settings_body(await request.read())
        unknown = set(updates) - {"stage_cpu"}
        if unknown:
            raise InferenceServerException(
                f"unknown profiling setting '{sorted(unknown)[0]}'"
            )
        value = updates.get("stage_cpu")
        if value is not None:
            if not isinstance(value, bool):
                raise InferenceServerException(
                    f"profiling setting 'stage_cpu' expects a boolean, "
                    f"got {value!r}"
                )
            if value:
                # enable() calibrates (a bounded ~20 ms clock-quantum
                # spin on some hosts) — run it off the event loop so
                # in-flight requests don't stall behind it
                await asyncio.get_running_loop().run_in_executor(
                    None, self.core.profiling.enable
                )
            else:
                self.core.profiling.disable()
        return web.json_response(self.core.profiling.config())

    async def handle_profile(self, request):
        """On-demand wall-stack sampling: ``GET /v2/debug/profile?
        duration_s=&hz=&format=collapsed|speedscope[&jax_trace_dir=]``.

        The sampler runs on an executor thread (the event loop keeps
        serving) and excludes its own thread from the samples; the
        measured-overhead guard inside WallProfiler caps its CPU cost.
        Nothing is installed when this endpoint is not called — profiling
        is strictly on-demand.
        """
        from client_tpu.observability.profiling import (
            WallProfiler,
            maybe_jax_trace,
        )

        query = request.query
        try:
            duration_s = float(query.get("duration_s", "1.0"))
            hz = float(query.get("hz", "99"))
        except ValueError as e:
            raise InferenceServerException(
                f"malformed profile request: {e}"
            ) from None
        if not 0 < duration_s <= 120:
            raise InferenceServerException(
                f"profile duration_s must be in (0, 120], got {duration_s}"
            )
        if not 1 <= hz <= 1000:
            raise InferenceServerException(
                f"profile hz must be in [1, 1000], got {hz}"
            )
        fmt = query.get("format", "collapsed")
        if fmt not in ("collapsed", "speedscope"):
            raise InferenceServerException(
                f"profile format must be 'collapsed' or 'speedscope', "
                f"got '{fmt}'"
            )
        jax_trace_dir = query.get("jax_trace_dir") or None
        if jax_trace_dir is not None:
            # a wire-controlled filesystem-write target must stay inside
            # the system temp dir — this endpoint must not hand any
            # client that can reach the HTTP port an arbitrary-path
            # write primitive (traces elsewhere: use jax.profiler
            # directly on the server side)
            import os
            import tempfile

            temp_root = os.path.realpath(tempfile.gettempdir())
            resolved = os.path.realpath(jax_trace_dir)
            if not (
                resolved == temp_root
                or resolved.startswith(temp_root + os.sep)
            ):
                raise InferenceServerException(
                    "jax_trace_dir must be inside the server's temp "
                    f"directory ({temp_root})"
                )
            jax_trace_dir = resolved
        if self._profiling_busy:
            return _error_response(
                "a profiling run is already in progress", status=409
            )
        self._profiling_busy = True
        try:
            profiler = WallProfiler(hz=hz)
            loop = asyncio.get_running_loop()

            def _run():
                with maybe_jax_trace(jax_trace_dir):
                    return profiler.run(duration_s)

            result = await loop.run_in_executor(None, _run)
        finally:
            self._profiling_busy = False
        headers = {
            "X-Profile-Samples": str(result.sample_count),
            "X-Profile-Hz-Effective": f"{result.hz_effective:.1f}",
        }
        if fmt == "speedscope":
            return web.json_response(result.speedscope(), headers=headers)
        return web.Response(
            text=result.collapsed(),
            content_type="text/plain",
            headers=headers,
        )

    # -- inference -----------------------------------------------------------

    async def handle_infer(self, request):
        # Drain fast path: reject before paying body read/decode cost
        # (_map_exception renders the 503 + Retry-After).
        self.core.reject_if_draining(request.match_info["model"])
        # aiohttp auto-decompresses request bodies per Content-Encoding
        # (gzip/deflate), so `body` is already plain here.
        body = await request.read()

        prof = self.core.profiling
        # one take() covers this request's decode AND encode brackets
        measured = prof.take()
        decode_cpu0 = prof.cpu_now() if measured else 0
        header_len = request.headers.get(HEADER_CONTENT_LENGTH)
        if header_len is not None:
            header_len = int(header_len)
            try:
                payload = json.loads(body[:header_len].decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                self.core.metrics.observe_frontend_error("http")
                raise InferenceServerException(
                    f"malformed inference request header: {e}"
                ) from None
            binary = body[header_len:]
        else:
            try:
                payload = json.loads(body.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                self.core.metrics.observe_frontend_error("http")
                raise InferenceServerException(
                    f"malformed inference request: {e}"
                ) from None
            binary = b""

        model_name = request.match_info["model"]
        # Trace sampling + W3C context extraction: a propagated sampled
        # traceparent correlates this server record with the client span.
        trace = self.core.trace_manager.begin(
            model_name,
            model_version=request.match_info.get("version", ""),
            traceparent=request.headers.get(TRACEPARENT_HEADER),
        )
        try:
            try:
                core_request = self._build_core_request(
                    model_name,
                    request.match_info.get("version", ""),
                    payload,
                    binary,
                )
            except InferenceServerException:
                # rejected before reaching the engine: the statistics
                # extension never sees it, the front-end counter does
                self.core.metrics.observe_frontend_error("http")
                raise
            if measured:
                prof.account(
                    "frontend_decode", prof.cpu_now() - decode_cpu0
                )
            core_request.trace = trace
            if trace is not None:
                trace.request_id = core_request.id
            try:
                core_response = await self.core.infer(core_request)
            except BaseException:
                if core_request.shm_ring is not None:
                    core_request.shm_ring.fail()
                raise
            accept = request.headers.get("Accept-Encoding", "")
            if measured:
                encode_cpu0 = prof.cpu_now()
                if core_request.shm_ring is not None:
                    core_response = core_request.shm_ring.complete(
                        core_response
                    )
                response = self._build_response(payload, core_response, accept)
                prof.account("encode", prof.cpu_now() - encode_cpu0)
            else:
                if core_request.shm_ring is not None:
                    core_response = core_request.shm_ring.complete(
                        core_response
                    )
                response = self._build_response(payload, core_response, accept)
        except BaseException as e:
            if trace is not None:
                trace.end(error=str(e))
            log = self.core.logger
            if log.verbose_hot:
                log.verbose(
                    "request",
                    model=model_name,
                    protocol="http",
                    status="error",
                    error=str(e),
                )
            raise
        if trace is not None:
            trace.end()
        log = self.core.logger
        if log.verbose_hot:
            log.verbose(
                "request",
                model=model_name,
                protocol="http",
                status="ok",
                request_id=core_request.id,
            )
        return response

    def _build_core_request(
        self, model_name, model_version, payload, binary
    ) -> CoreRequest:
        parameters = dict(payload.get("parameters", {}))
        binary_output_default = bool(parameters.pop("binary_data_output", False))
        request = CoreRequest(
            model_name=model_name,
            model_version=model_version,
            id=payload.get("id", ""),
            parameters=parameters,
        )
        offset = 0
        for tensor in payload.get("inputs", []):
            params = tensor.get("parameters", {})
            name = tensor.get("name")
            datatype = tensor.get("datatype")
            shape = [int(s) for s in tensor.get("shape", [])]
            if name is None or datatype is None:
                raise InferenceServerException(
                    "inference input must have 'name' and 'datatype'"
                )
            raw = None
            json_data = None
            shm_region = params.get("shared_memory_region")
            if "binary_data_size" in params:
                size = int(params["binary_data_size"])
                if offset + size > len(binary):
                    raise InferenceServerException(
                        f"binary section truncated for input '{name}'"
                    )
                raw = binary[offset : offset + size]
                offset += size
            elif shm_region is None:
                json_data = tensor.get("data")
            request.inputs.append(
                self.core.decode_input(
                    name,
                    datatype,
                    shape,
                    raw=raw,
                    json_data=json_data,
                    shm_region=shm_region,
                    shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                    shm_offset=int(params.get("shared_memory_offset", 0)),
                )
            )
        for out in payload.get("outputs", []):
            params = out.get("parameters", {})
            request.outputs.append(
                CoreRequestedOutput(
                    name=out["name"],
                    binary_data=bool(
                        params.get("binary_data", binary_output_default)
                    ),
                    classification=int(params.get("classification", 0)),
                    shm_region=params.get("shared_memory_region"),
                    shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                    shm_offset=int(params.get("shared_memory_offset", 0)),
                )
            )
        # shm-ring requests (shm_ring_region/slot/seq parameters): inputs
        # come from the ring slot, the response goes back into it
        shm_ring.attach(self.core, request)
        return request

    def _build_response(self, payload, core_response, accept: str) -> web.Response:
        requested = {
            o.get("name"): o.get("parameters", {})
            for o in payload.get("outputs", [])
        }
        # Spec default for JSON requests is JSON output; only the explicit
        # binary_data_output request parameter flips unlisted outputs to
        # binary (the client sets it whenever outputs are omitted).
        want_binary_default = bool(
            payload.get("parameters", {}).get("binary_data_output", False)
        )
        header: Dict[str, Any] = {
            "model_name": core_response.model_name,
            "model_version": core_response.model_version,
            "outputs": [],
        }
        if core_response.id:
            header["id"] = core_response.id
        if core_response.parameters:
            header["parameters"] = core_response.parameters
        chunks: List[bytes] = []
        for tensor in core_response.outputs:
            out_json: Dict[str, Any] = {
                "name": tensor.name,
                "datatype": tensor.datatype,
                "shape": tensor.shape,
            }
            if tensor.name in core_response.shm_outputs:
                region, size, shm_offset = core_response.shm_outputs[tensor.name]
                out_json["parameters"] = {
                    "shared_memory_region": region,
                    "shared_memory_byte_size": size,
                }
                if shm_offset:
                    out_json["parameters"]["shared_memory_offset"] = shm_offset
            else:
                params = requested.get(tensor.name, {})
                binary = bool(params.get("binary_data", want_binary_default))
                if tensor.datatype == "BF16" and not binary:
                    binary = True  # BF16 has no JSON form
                if binary:
                    if tensor.datatype == "BYTES":
                        raw = serialize_byte_tensor(tensor.data).tobytes()
                    else:
                        raw = np.ascontiguousarray(tensor.data).tobytes()
                    chunks.append(raw)
                    out_json["parameters"] = {"binary_data_size": len(raw)}
                else:
                    if tensor.datatype == "BYTES":
                        out_json["data"] = [
                            b.decode("utf-8", errors="replace")
                            for b in tensor.data.reshape(-1)
                        ]
                    else:
                        out_json["data"] = tensor.data.reshape(-1).tolist()
            header["outputs"].append(out_json)

        header_bytes = json.dumps(header).encode("utf-8")
        response_headers = {"Content-Type": "application/octet-stream"}
        if chunks:
            body = b"".join([header_bytes] + chunks)
            response_headers[HEADER_CONTENT_LENGTH] = str(len(header_bytes))
        else:
            body = header_bytes
            response_headers["Content-Type"] = "application/json"

        accept = accept.lower()
        if "gzip" in accept:
            body = gzip.compress(body)
            response_headers["Content-Encoding"] = "gzip"
        elif "deflate" in accept:
            body = zlib.compress(body)
            response_headers["Content-Encoding"] = "deflate"
        return web.Response(body=body, headers=response_headers)


async def serve_http(
    core: ServerCore,
    host: str = "0.0.0.0",
    port: int = 8000,
    chaos: Optional[object] = None,
) -> web.AppRunner:
    """Start the HTTP server; returns the runner (caller owns shutdown).

    ``chaos`` (a :class:`client_tpu.resilience.ChaosPolicy`) enables
    fault injection for resilience testing."""
    server = HttpServer(core, chaos=chaos)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner
