"""Server-side shared-memory region manager.

Tracks regions registered by clients over the system (POSIX) and TPU
shared-memory extensions and maps them into the server process. The server
reads request inputs from, and writes requested outputs into, these mappings
— the sideband data plane of SURVEY.md §1/L1.

TPU regions are shared pinned host buffers: the raw handle (produced by
client_tpu.utils.tpu_shared_memory.get_raw_handle) is a JSON document naming
the POSIX shm key backing the buffer. On the server they are mapped like
system regions but tracked separately so status/unregister semantics match
the per-kind endpoints, and so the JAX backend can import them zero-copy via
DLPack.
"""

import json
import mmap
import os
import threading
from typing import Any, Dict, Optional

from client_tpu.utils import InferenceServerException

SHM_DIR = "/dev/shm"


def _shm_path(key: str) -> str:
    return os.path.join(SHM_DIR, key.lstrip("/"))


class _Region:
    def __init__(
        self,
        name: str,
        kind: str,
        key: str,
        offset: int,
        byte_size: int,
        device_id: int = 0,
    ):
        self.name = name
        self.kind = kind  # "system" | "tpu"
        self.key = key
        self.offset = offset
        self.byte_size = byte_size
        self.device_id = device_id
        path = _shm_path(key)
        try:
            self._fd = os.open(path, os.O_RDWR)
        except OSError as e:
            raise InferenceServerException(
                f"failed to open shared memory region '{name}' "
                f"(key '{key}'): {e}"
            ) from None
        try:
            total = os.fstat(self._fd).st_size
            if offset + byte_size > total:
                raise InferenceServerException(
                    f"shared memory region '{name}' (key '{key}') is "
                    f"{total} bytes; cannot map offset {offset} + "
                    f"byte_size {byte_size}"
                )
            self._map = mmap.mmap(self._fd, total)
        except Exception:
            os.close(self._fd)
            raise

    def view(self, offset: int, byte_size: int) -> memoryview:
        start = self.offset + offset
        end = start + byte_size
        if offset < 0 or byte_size < 0 or end > self.offset + self.byte_size:
            raise InferenceServerException(
                f"invalid offset/byte_size for shared memory region "
                f"'{self.name}': {offset}+{byte_size} exceeds region size "
                f"{self.byte_size}"
            )
        return memoryview(self._map)[start:end]

    def close(self) -> None:
        try:
            self._map.close()
        except BufferError:
            # Zero-copy views into the mapping are still alive (decode_input
            # hands np.frombuffer views of the region to in-flight
            # requests). Drop our reference instead: the mapping unmaps
            # when the last view dies, and the fd/name release now.
            pass
        finally:
            os.close(self._fd)


class SharedMemoryManager:
    """name -> mapped region registry (thread-safe)."""

    def __init__(self):
        self._regions: Dict[str, _Region] = {}
        self._lock = threading.Lock()

    # -- registration -------------------------------------------------------

    def register_system(
        self, name: str, key: str, offset: int, byte_size: int
    ) -> None:
        self._register(_Region(name, "system", key, offset, byte_size))

    def register_tpu(
        self, name: str, raw_handle: bytes, device_id: int, byte_size: int
    ) -> None:
        try:
            handle = json.loads(bytes(raw_handle).decode("utf-8"))
            key = handle["shm_key"]
        except (UnicodeDecodeError, json.JSONDecodeError, KeyError) as e:
            raise InferenceServerException(
                f"malformed TPU shared-memory raw handle for region "
                f"'{name}': {e}"
            ) from None
        handle_size = int(handle.get("byte_size", byte_size))
        if handle_size < byte_size:
            raise InferenceServerException(
                f"TPU shared-memory region '{name}': registered byte_size "
                f"{byte_size} exceeds handle's buffer size {handle_size}"
            )
        self._register(
            _Region(name, "tpu", key, 0, byte_size, device_id=device_id)
        )

    def _register(self, region: _Region) -> None:
        with self._lock:
            if region.name in self._regions:
                existing = self._regions[region.name]
                # Re-registration with identical parameters is idempotent.
                if (
                    existing.kind == region.kind
                    and existing.key == region.key
                    and existing.offset == region.offset
                    and existing.byte_size == region.byte_size
                ):
                    region.close()
                    return
                region.close()
                raise InferenceServerException(
                    f"shared memory region '{region.name}' already registered "
                    "with different parameters"
                )
            self._regions[region.name] = region

    # -- unregistration -----------------------------------------------------

    def unregister(self, name: str, kind: Optional[str] = None) -> None:
        with self._lock:
            region = self._regions.get(name)
            if region is None:
                return  # Triton semantics: unregister of unknown is a no-op
            if kind is not None and region.kind != kind:
                raise InferenceServerException(
                    f"shared memory region '{name}' is of kind "
                    f"'{region.kind}', not '{kind}'"
                )
            del self._regions[name]
        region.close()

    def unregister_all(self, kind: Optional[str] = None) -> None:
        with self._lock:
            victims = [
                n
                for n, r in self._regions.items()
                if kind is None or r.kind == kind
            ]
            regions = [self._regions.pop(n) for n in victims]
        for r in regions:
            r.close()

    # -- access -------------------------------------------------------------

    def status(self, kind: str, name: str = "") -> Dict[str, Dict[str, Any]]:
        with self._lock:
            result = {}
            for n, r in self._regions.items():
                if r.kind != kind or (name and n != name):
                    continue
                if kind == "system":
                    result[n] = {
                        "name": n,
                        "key": r.key,
                        "offset": r.offset,
                        "byte_size": r.byte_size,
                    }
                else:
                    result[n] = {
                        "name": n,
                        "device_id": r.device_id,
                        "byte_size": r.byte_size,
                        "key": r.key,
                    }
            return result

    def region(self, name: str) -> Optional[_Region]:
        """The live region object for ``name`` (None when unregistered).
        Identity is stable per registration — the shm-ring registry keys
        its cache on it so re-registration invalidates cleanly."""
        with self._lock:
            return self._regions.get(name)

    def read(self, name: str, offset: int, byte_size: int) -> memoryview:
        with self._lock:
            region = self._regions.get(name)
        if region is None:
            raise InferenceServerException(
                f"Unable to find shared memory region: '{name}'"
            )
        return region.view(offset, byte_size)

    def write(self, name: str, offset: int, data: bytes) -> None:
        view = self.read(name, offset, len(data))
        view[:] = data
