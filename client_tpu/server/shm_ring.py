"""Server side of the fixed-layout shared-memory ring.

Framing and layout live in :mod:`client_tpu.utils.tpu_shared_memory.ring`
(one source of truth for both ends); this module adds what only the
server knows: resolving ``shm_ring_region`` parameters against the
registered-region table, validating slot state/sequence before trusting
client-written bytes (a torn or stale write is a clean INVALID_ARGUMENT,
never a crash or a wrong answer), and writing response tensors back into
the slot so the wire acknowledgement stays tens of bytes.

Front-end contract (all front-ends share it):

* after building the CoreRequest, call :func:`attach` — it pops the ring
  parameters (they must never reach the batch signature: the slot number
  differs per request and would fragment batches), reads the slot's
  tensors into ``request.inputs`` zero-copy, and leaves a
  :class:`RingTicket` on ``request.shm_ring``;
* after the core produces a CoreResponse, call ``ticket.complete(resp)``
  — it packs the outputs into the same slot and returns the slim
  acknowledgement response to serialize instead.

A ring request against a server that no longer has the region (restart
with a live client ring) fails with an *unavailable* message so both
protocols surface a retryable 503/UNAVAILABLE — the client re-registers
and carries on; the bytes in its mapping are untouched.
"""

import struct
import threading
from typing import Any, Dict, List, Optional

from client_tpu.utils import InferenceServerException
from client_tpu.utils.tpu_shared_memory import ring as ringfmt

_SLOT_HEADER = struct.Struct("<IIII")


class RingTicket:
    """One in-flight ring request on the server side.

    The ticket is the ONCE-ONLY completion surface: ``complete``/
    ``fail`` close the read_request accounting exactly once no matter
    how many error paths also call ``fail()`` afterwards (the in-use
    gauge books per ticket, not per slot peek)."""

    __slots__ = ("_ring", "slot", "seq", "_open")

    def __init__(self, ring: "ServerShmRing", slot: int, seq: int):
        self._ring = ring
        self.slot = slot
        self.seq = seq
        self._open = True

    def complete(self, response) -> Any:
        """Pack ``response`` outputs into the slot; returns the slim
        acknowledgement CoreResponse to put on the wire. Raises (with
        the slot marked errored and the accounting closed) when the
        response does not fit or the slot was re-staged underneath us —
        a stale completion must never scribble over a newer request."""
        if not self._open:
            raise InferenceServerException(
                f"shm ring '{self._ring.name}' slot {self.slot} ticket "
                "already completed"
            )
        self._open = False
        return self._ring.write_response(self.slot, self.seq, response)

    def fail(self) -> None:
        """Mark the slot errored (the RPC error carries the details).
        Idempotent: later calls (or a call after ``complete``) no-op."""
        if self._open:
            self._open = False
            self._ring.fail(self.slot, self.seq)


class ServerShmRing:
    """A validated ring over one registered region's mapping."""

    def __init__(self, name: str, region, metrics=None):
        import numpy as np

        self.name = name
        self._region = region
        buf = region.view(0, region.byte_size)
        self.slot_size, self.n_slots = ringfmt.read_region_header(buf)
        self._buf = buf
        # byte view of the whole mapping, for output-aliasing detection
        # (np.may_share_memory is a cheap bounds check)
        self._np_view = np.frombuffer(buf, dtype=np.uint8)
        self._metrics = metrics
        self._lock = threading.Lock()
        self._in_use = 0

    @property
    def region(self):
        return self._region

    def _slot_view(self, slot: int):
        if not 0 <= slot < self.n_slots:
            raise InferenceServerException(
                f"shm ring '{self.name}' has {self.n_slots} slots; "
                f"slot {slot} is out of range"
            )
        off = ringfmt.slot_offset(slot, self.slot_size)
        return self._buf[off : off + self.slot_size]

    def _book(self, delta: int) -> None:
        with self._lock:
            self._in_use += delta
            value = self._in_use
        if self._metrics is not None:
            self._metrics.set_ring_slots(self.name, value)

    def read_request(self, slot: int, seq: int) -> List[Any]:
        """Validate + read the request tensors from ``slot`` (zero-copy
        views into the mapping). Transitions the slot to BUSY."""
        from client_tpu.server.core import CoreTensor

        view = self._slot_view(slot)
        state, slot_seq, payload_len, _ = _SLOT_HEADER.unpack_from(view, 0)
        if state != ringfmt.STATE_REQUEST:
            raise InferenceServerException(
                f"shm ring '{self.name}' slot {slot} is not in the "
                f"request-ready state (state {state}): torn write or "
                "double submission"
            )
        if slot_seq != seq:
            raise InferenceServerException(
                f"shm ring '{self.name}' slot {slot} carries seq "
                f"{slot_seq} but the request names seq {seq}: stale or "
                "torn slot write"
            )
        tensors = []
        try:
            for name, datatype, shape, data in ringfmt.unpack_tensors(
                view[ringfmt.SLOT_HEADER_SIZE :], payload_len
            ):
                if datatype != "BYTES":
                    # read-only view, same contract as decode_input's shm
                    # path: a model mutating its input raises instead of
                    # corrupting the client's slot
                    data = data.toreadonly()
                arr = ringfmt.view_as_numpy(datatype, shape, data)
                tensors.append(CoreTensor(name, datatype, list(shape), arr))
        except InferenceServerException:
            raise
        except (ValueError, TypeError) as e:
            # inconsistent framing that passed the bounds checks (e.g.
            # data_len not matching shape x dtype): the client's fault,
            # surfaced cleanly — never a bare 500
            raise InferenceServerException(
                f"shm ring '{self.name}' slot {slot} framing is "
                f"inconsistent: {e}"
            ) from None
        _SLOT_HEADER.pack_into(view, 0, ringfmt.STATE_BUSY, seq, payload_len, 0)
        self._book(+1)
        return tensors

    def write_response(self, slot: int, seq: int, response) -> Any:
        """Pack the response outputs into the slot and return the slim
        wire response (no tensor payloads).

        Called exactly once per read ticket (RingTicket gates this), so
        it always closes the read's in-use accounting. The slot must
        still be (BUSY, seq): if the client abandoned the request and
        re-staged the slot, this stale completion is DROPPED with an
        error instead of corrupting the newer request's bytes."""
        from client_tpu.server.core import CoreResponse

        import numpy as np

        view = self._slot_view(slot)
        self._book(-1)
        state, slot_seq, _, _ = _SLOT_HEADER.unpack_from(view, 0)
        if state != ringfmt.STATE_BUSY or slot_seq != seq:
            raise InferenceServerException(
                f"shm ring '{self.name}' slot {slot} was re-staged while "
                f"its request executed (state {state}, seq {slot_seq} vs "
                f"{seq}): stale completion dropped"
            )
        payload = view[ringfmt.SLOT_HEADER_SIZE :]
        # A model may return (a view of) its zero-copy ring input — e.g.
        # identity passthrough. Packing that back into the same slot
        # would be a self-overlapping copy (the response framing shifts
        # the data bytes), so snapshot any output aliasing the mapping.
        tensors = []
        for t in response.outputs:
            data = t.data
            if (
                isinstance(data, np.ndarray)
                and data.dtype.kind != "O"
                and np.may_share_memory(data, self._np_view)
            ):
                data = data.copy()
            tensors.append((t.name, data))
        try:
            payload_len = ringfmt.pack_tensors(payload, tensors)
        except Exception:
            # accounting already closed above; just mark our generation
            _SLOT_HEADER.pack_into(view, 0, ringfmt.STATE_ERROR, seq, 0, 0)
            raise
        _SLOT_HEADER.pack_into(
            view, 0, ringfmt.STATE_RESPONSE, seq, payload_len, 0
        )
        return CoreResponse(
            model_name=response.model_name,
            model_version=response.model_version,
            id=response.id,
            outputs=[],
            parameters={
                **response.parameters,
                ringfmt.PARAM_SLOT: slot,
                ringfmt.PARAM_SEQ: seq,
                ringfmt.PARAM_BYTES: payload_len,
            },
        )

    def fail(self, slot: int, seq: int) -> None:
        """Close an abandoned read ticket: books the in-use accounting
        (once — RingTicket gates callers) and marks the slot errored
        only while it is still OUR (BUSY, seq) generation, so a
        re-staged slot or an already-written response is never
        clobbered."""
        self._book(-1)
        try:
            view = self._slot_view(slot)
        except InferenceServerException:
            return
        state, slot_seq, _, _ = _SLOT_HEADER.unpack_from(view, 0)
        if state != ringfmt.STATE_BUSY or slot_seq != seq:
            return
        _SLOT_HEADER.pack_into(view, 0, ringfmt.STATE_ERROR, seq, 0, 0)


class RingRegistry:
    """name -> ServerShmRing cache over the shared-memory manager.

    Rings are validated once per registration: the cache entry is keyed
    on the *region object*, so an unregister/re-register cycle (or a
    server restart, which empties the manager) can never serve a stale
    mapping."""

    def __init__(self, shm_manager, metrics=None):
        self._shm = shm_manager
        self._metrics = metrics
        self._rings: Dict[str, ServerShmRing] = {}
        self._lock = threading.Lock()

    def prune(self) -> None:
        """Evict cached rings whose region is gone or replaced — without
        this, each ring pins its full mapping (and gauge child) for the
        server's lifetime: ring names rotate per client run, so the
        cache would only ever grow. Cheap (the live ring set is small);
        runs on every lookup."""
        with self._lock:
            stale = [
                name
                for name, ring in self._rings.items()
                if self._shm.region(name) is not ring.region
            ]
            for name in stale:
                del self._rings[name]
        if self._metrics is not None:
            for name in stale:
                self._metrics.remove_ring_region(name)

    def get(self, name: str) -> ServerShmRing:
        self.prune()
        region = self._shm.region(name)
        if region is None:
            raise InferenceServerException(
                f"shm ring region '{name}' is unavailable: not registered "
                "with this server (was the server restarted?); re-register "
                "the ring region and retry"
            )
        with self._lock:
            ring = self._rings.get(name)
            if ring is not None and ring.region is region:
                return ring
        ring = ServerShmRing(name, region, metrics=self._metrics)
        with self._lock:
            current = self._rings.get(name)
            if current is not None and current.region is region:
                return current
            self._rings[name] = ring
        return ring


def attach(core, request) -> Optional[RingTicket]:
    """Resolve ring parameters on a decoded CoreRequest (if any).

    Pops the ``shm_ring_*`` parameters, reads the slot's tensors into
    ``request.inputs``, and stores the ticket on ``request.shm_ring``.
    Returns the ticket (None for non-ring requests). Raises
    InferenceServerException on any protocol violation.
    """
    params = request.parameters
    if not params or ringfmt.PARAM_REGION not in params:
        return None
    region_name = params.pop(ringfmt.PARAM_REGION)
    slot = params.pop(ringfmt.PARAM_SLOT, None)
    seq = params.pop(ringfmt.PARAM_SEQ, 0)
    if not isinstance(region_name, str) or not isinstance(slot, int):
        raise InferenceServerException(
            "shm ring requests need string 'shm_ring_region' and integer "
            "'shm_ring_slot' parameters"
        )
    if request.inputs:
        raise InferenceServerException(
            "shm ring requests must not also carry inline inputs"
        )
    ring = core.shm_rings.get(region_name)
    request.inputs = ring.read_request(int(slot), int(seq))
    ticket = RingTicket(ring, int(slot), int(seq))
    request.shm_ring = ticket
    return ticket
