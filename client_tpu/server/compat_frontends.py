"""TensorFlow-Serving and TorchServe REST compatibility front-ends.

Thin protocol adapters over :class:`ServerCore`, giving the perf harness's
``tensorflow_serving`` / ``torchserve`` backends (reference
client_backend/tensorflow_serving/, client_backend/torchserve/) live
endpoints to drive:

- TFS row format (REST API): ``POST /v1/models/<m>:predict`` with
  ``{"instances": [...]}`` -> ``{"predictions": [...]}``;
  ``GET /v1/models/<m>`` (status) and ``GET /v1/models/<m>/metadata``
  (simplified signature block carrying name/dtype/shape per tensor).
- TorchServe inference API: ``POST /predictions/<m>`` with a raw tensor
  body (or a JSON list) -> JSON prediction list; ``GET /ping``.

These adapt the WIRE protocols; model semantics stay KServe (dtypes and
shapes come from the model's own metadata).
"""

import json
from typing import Any, Dict

import numpy as np
from aiohttp import web

from client_tpu.server.core import CoreRequest, CoreTensor, ServerCore
from client_tpu.utils import (
    KSERVE_TO_TF_DTYPE as _TF_DTYPES,
    InferenceServerException,
    triton_to_np_dtype,
)


class CompatFrontends:
    """Registers the TFS + TorchServe routes on the aiohttp app."""

    def __init__(self, core: ServerCore):
        self.core = core

    def add_routes(self, app: web.Application, guarded) -> None:
        r = app.router
        r.add_get("/ping", guarded(self.handle_ping))
        r.add_post("/predictions/{model}", guarded(self.handle_torchserve))
        # ':' is not an aiohttp separator, so '<name>:predict' arrives as
        # one path segment.
        r.add_get("/v1/models/{model_op}", guarded(self.handle_tfs_get))
        r.add_get(
            "/v1/models/{model}/metadata", guarded(self.handle_tfs_metadata)
        )
        r.add_post("/v1/models/{model_op}", guarded(self.handle_tfs_post))

    # -- TorchServe ----------------------------------------------------------

    async def handle_ping(self, request):
        return web.json_response(
            {"status": "Healthy" if self.core.live else "Unhealthy"}
        )

    async def handle_torchserve(self, request):
        model_name = request.match_info["model"]
        model = self.core.repository.get(model_name)
        if len(model.inputs) != 1:
            raise InferenceServerException(
                f"torchserve adapter serves single-input models; "
                f"'{model_name}' declares {len(model.inputs)}"
            )
        desc = model.inputs[0]
        body = await request.read()
        shape = self._resolved_shape(model, desc)
        content_type = request.headers.get("Content-Type", "")
        if content_type.startswith("application/json"):
            values = json.loads(body)
            arr = np.asarray(values, dtype=triton_to_np_dtype(
                desc["datatype"]))
        else:
            np_dtype = triton_to_np_dtype(desc["datatype"])
            arr = np.frombuffer(body, dtype=np_dtype)
            try:
                arr = arr.reshape(shape)
            except ValueError:
                arr = arr.reshape([1, -1] if model.max_batch_size > 0
                                  else [-1])
        if model.max_batch_size > 0 and arr.ndim == len(desc["shape"]):
            # Batchable models declare shapes without the batch dim; a bare
            # instance gains it. Non-batchable shapes are already complete.
            arr = arr[None]
        response = await self.core.infer(
            CoreRequest(
                model_name=model_name,
                inputs=[
                    CoreTensor(
                        desc["name"],
                        desc["datatype"],
                        list(arr.shape),
                        arr,
                    )
                ],
            )
        )
        out = response.outputs[0].data
        return web.json_response(np.asarray(out).tolist())

    # -- TensorFlow Serving --------------------------------------------------

    async def handle_tfs_get(self, request):
        model_op = request.match_info["model_op"]
        model = self.core.repository.get(model_op)
        ready = self.core.repository.is_ready(model.name, "")
        return web.json_response(
            {
                "model_version_status": [
                    {
                        "version": model.version,
                        "state": "AVAILABLE" if ready else "LOADING",
                        "status": {"error_code": "OK", "error_message": ""},
                    }
                ]
            }
        )

    async def handle_tfs_metadata(self, request):
        model = self.core.repository.get(request.match_info["model"])

        def tensor_block(descs):
            block: Dict[str, Any] = {}
            for d in descs:
                dims = [{"size": str(s)} for s in ([-1] + list(d["shape"])
                        if model.max_batch_size > 0 else d["shape"])]
                block[d["name"]] = {
                    "dtype": _TF_DTYPES.get(d["datatype"], "DT_INVALID"),
                    "tensor_shape": {"dim": dims},
                    "name": d["name"],
                }
            return block

        return web.json_response(
            {
                "model_spec": {"name": model.name,
                               "version": model.version},
                "metadata": {
                    "signature_def": {
                        "signature_def": {
                            "serving_default": {
                                "inputs": tensor_block(model.inputs),
                                "outputs": tensor_block(model.outputs),
                            }
                        }
                    }
                },
            }
        )

    async def handle_tfs_post(self, request):
        model_op = request.match_info["model_op"]
        if not model_op.endswith(":predict"):
            raise InferenceServerException(
                f"unsupported TFS verb in '{model_op}' (only :predict)"
            )
        model_name = model_op[: -len(":predict")]
        model = self.core.repository.get(model_name)
        payload = json.loads(await request.read())
        inputs = []
        if "instances" in payload:
            # Row format: one entry per batch row. Single-input models take
            # bare values; multi-input models take {name: value} objects.
            rows = payload["instances"]
            if not rows:
                raise InferenceServerException("'instances' is empty")
            if isinstance(rows[0], dict) and set(rows[0].keys()) != {"b64"}:
                names = rows[0].keys()
                for i, row in enumerate(rows):
                    if not isinstance(row, dict) or row.keys() != names:
                        raise InferenceServerException(
                            f"instance {i} does not carry the same inputs "
                            f"as instance 0 ({sorted(names)})"
                        )
                for name in names:
                    desc = self._input_desc(model, name)
                    arr = self._decode_values(
                        desc, [row[name] for row in rows]
                    )
                    inputs.append(
                        CoreTensor(name, desc["datatype"], list(arr.shape),
                                   arr)
                    )
            else:
                if len(model.inputs) != 1:
                    raise InferenceServerException(
                        "bare 'instances' rows need a single-input model"
                    )
                desc = model.inputs[0]
                arr = self._decode_values(desc, rows)
                inputs.append(
                    CoreTensor(desc["name"], desc["datatype"],
                               list(arr.shape), arr)
                )
        elif "inputs" in payload:
            # Column format: {name: full tensor} (or a bare tensor for
            # single-input models).
            cols = payload["inputs"]
            if not isinstance(cols, dict):
                desc = model.inputs[0]
                arr = np.asarray(
                    cols, dtype=triton_to_np_dtype(desc["datatype"])
                )
                cols = {desc["name"]: arr}
            for name, values in cols.items():
                desc = self._input_desc(model, name)
                arr = self._decode_values(desc, values)
                inputs.append(
                    CoreTensor(name, desc["datatype"], list(arr.shape), arr)
                )
        else:
            raise InferenceServerException(
                "TFS predict body needs 'instances' or 'inputs'"
            )

        response = await self.core.infer(
            CoreRequest(model_name=model_name, inputs=inputs)
        )

        def encode(t):
            arr = np.asarray(t.data)
            if t.datatype == "BYTES":
                import base64

                flat = [
                    {"b64": base64.b64encode(
                        v if isinstance(v, bytes) else str(v).encode()
                    ).decode("ascii")}
                    for v in arr.reshape(-1)
                ]
                return np.array(flat, dtype=object).reshape(
                    arr.shape
                ).tolist()
            return arr.tolist()

        if len(response.outputs) == 1:
            predictions = encode(response.outputs[0])
        else:
            predictions = {t.name: encode(t) for t in response.outputs}
        return web.json_response({"predictions": predictions})

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _decode_values(desc, values):
        """JSON values -> ndarray; TFS string tensors arrive as
        {"b64": ...} objects (the REST API's binary encoding)."""
        if desc["datatype"] == "BYTES":
            import base64

            def decode(v):
                if isinstance(v, dict) and "b64" in v:
                    return base64.b64decode(v["b64"])
                if isinstance(v, str):
                    return v.encode("utf-8")
                return bytes(v)

            flat = np.asarray(values, dtype=object)
            return np.array(
                [decode(v) for v in flat.reshape(-1)], dtype=object
            ).reshape(flat.shape)
        return np.asarray(values, dtype=triton_to_np_dtype(desc["datatype"]))

    @staticmethod
    def _input_desc(model, name):
        for d in model.inputs:
            if d["name"] == name:
                return d
        raise InferenceServerException(
            f"model '{model.name}' has no input '{name}'"
        )

    @staticmethod
    def _resolved_shape(model, desc):
        shape = [1] + [int(s) for s in desc["shape"]]
        return [s if s > 0 else -1 for s in shape]
