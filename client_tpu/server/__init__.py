"""An in-repo KServe v2 inference server backed by JAX models.

The reference client stack is tested against a live Triton server and ships
an in-process ``triton_c_api`` backend for network-free measurement
(reference src/c++/perf_analyzer/client_backend/triton_c_api/). This package
plays both roles for client_tpu:

- ``client_tpu.server.http_server`` / ``grpc_server``: real network servers
  speaking the KServe v2 HTTP/REST and gRPC protocols (health, metadata,
  infer with binary tensors, decoupled streaming, shared-memory registration,
  statistics, repository control, trace/log settings);
- ``client_tpu.server.core.ServerCore``: the protocol-independent engine,
  usable in-process for overhead-free baselines;
- ``client_tpu.server.models``: built-in JAX models (add_sub "simple",
  identity, and the model-zoo adapters from ``client_tpu.models``).

It is a genuine (single-node) serving runtime for JAX/XLA models on TPU, not
a mock: tensors move through the same dtype/serialization layer the clients
use, and the TPU shared-memory data plane is fully honored.
"""

from client_tpu.server.core import ServerCore  # noqa: F401
from client_tpu.server.model_repository import Model, ModelRepository  # noqa: F401
