"""Protocol-independent server engine.

Both the HTTP and gRPC front-ends reduce a request to :class:`CoreRequest`
(name->ndarray inputs plus requested-output descriptors), hand it to
:meth:`ServerCore.infer` / :meth:`ServerCore.infer_decoupled`, and serialize
the returned :class:`CoreResponse` objects back onto their wire. Statistics
are accounted the way Triton's statistics extension reports them
(success/fail/queue/compute_input/compute_infer/compute_output cumulative
count+ns; reference SURVEY.md §5 observability).
"""

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

import numpy as np

from client_tpu.scheduling import (
    SCHEDULING_PARAM_KEYS,
    TIMEOUT_ACTION_REJECT,
    AdmissionGate,
    PriorityQueue,
    QueueFullError,
    QueuePolicy,
    QueueTimeoutError,
    RateLimiter,
    SchedulingError,
)
from client_tpu.lifecycle import DrainController, ServerDrainingError
from client_tpu.server.model_repository import Model, ModelRepository
from client_tpu.server.shm import SharedMemoryManager
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    np_to_triton_dtype,
    num_elements,
    serialize_byte_tensor,
    triton_to_np_dtype,
)

SERVER_NAME = "client_tpu_server"
SERVER_VERSION = "0.1.0"
SERVER_EXTENSIONS = [
    "classification",
    "sequence",
    "model_repository",
    "model_repository(unload_dependents)",
    "schedule_policy",
    "model_configuration",
    "system_shared_memory",
    "cuda_shared_memory",
    "tpu_shared_memory",
    "binary_tensor_data",
    "parameters",
    "statistics",
    "trace",
    "logging",
    # rolling-window quantiles + SLO burn rates (GET /v2/debug/slo, the
    # tpu_rolling_latency_seconds / tpu_slo_* gauge families); advertised
    # by both front-ends' server-metadata responses
    "live_telemetry",
    # mesh-sharded multi-device execution (client_tpu.parallel): models
    # declare a mesh + per-tensor shardings, the server resolves and
    # executes them, topology rides server metadata (HTTP), the model
    # config parameters map (both protocols), and /v2/debug/state; per-
    # device busy-ns exports as tpu_device_compute_ns_total{device}
    "sharding",
]


@dataclass(slots=True)
class CoreTensor:
    name: str
    datatype: str
    shape: List[int]
    data: np.ndarray  # host ndarray (object dtype for BYTES)


@dataclass(slots=True)
class CoreRequestedOutput:
    name: str
    binary_data: bool = False
    classification: int = 0
    shm_region: Optional[str] = None
    shm_byte_size: int = 0
    shm_offset: int = 0


@dataclass(slots=True)
class CoreRequest:
    model_name: str
    model_version: str = ""
    id: str = ""
    inputs: List[CoreTensor] = field(default_factory=list)
    outputs: List[CoreRequestedOutput] = field(default_factory=list)
    parameters: Dict[str, Any] = field(default_factory=dict)
    # server trace attached by the front-end (observability.ServerTrace);
    # the execution paths add queue/compute stage events to it
    trace: Optional[Any] = None
    # scheduling fields stamped at admission (QueuePolicy.stamp): the
    # effective queue level (1 = highest) and the absolute queue deadline
    # in monotonic ns (None = no deadline)
    priority_level: int = 0
    deadline_ns: Optional[int] = None
    # shm-ring ticket (server.shm_ring.RingTicket) attached by the
    # front-end when the request sourced its inputs from a ring slot;
    # the front-end routes the response back through ticket.complete()
    shm_ring: Optional[Any] = None


def _trace_id_of(request) -> str:
    """The request's trace id ("" when untraced) — rides the success
    booking into the metrics layer as the duration histogram's
    OpenMetrics exemplar, linking a ``/metrics`` bucket to the same
    request's ``/v2/debug/requests`` evidence."""
    trace = request.trace
    return trace.trace_id if trace is not None else ""


def _trace_stages(
    trace, queue_start_ns: int, compute_start_ns: int,
    compute_end_ns: int, request_end_ns: int,
) -> None:
    """Stamp the Triton-style stage timestamps onto a server trace
    (no-op for untraced requests). REQUEST_START was recorded by the
    front-end when it accepted the request."""
    if trace is None:
        return
    trace.event("QUEUE_START", queue_start_ns)
    trace.event("COMPUTE_START", compute_start_ns)
    trace.event("COMPUTE_END", compute_end_ns)
    trace.event("REQUEST_END", request_end_ns)


@dataclass(slots=True)
class CoreResponse:
    model_name: str
    model_version: str
    id: str
    outputs: List[CoreTensor]
    parameters: Dict[str, Any] = field(default_factory=dict)
    # outputs redirected to shared memory: name -> (region, byte_size, offset)
    shm_outputs: Dict[str, Any] = field(default_factory=dict)


class _Stats:
    """Cumulative per-model statistics (counts + ns).

    ``metrics`` (a :class:`client_tpu.server.metrics.ServerMetrics`) gets
    the same events as the counters — every booking path feeds both, so
    the statistics extension and the Prometheus families can never
    disagree. Metrics calls happen outside ``self.lock``.
    """

    FIELDS = ("success", "fail", "queue", "compute_input", "compute_infer", "compute_output")

    def __init__(self, metrics=None, model_name: str = ""):
        self._metrics = metrics
        self._model = model_name
        self.lock = threading.Lock()
        self.counts = {f: 0 for f in self.FIELDS}
        self.ns = {f: 0 for f in self.FIELDS}
        self.inference_count = 0
        self.execution_count = 0
        self.last_inference = 0
        # Decoupled response statistics, keyed by response index (Triton's
        # response_stats map: key "0" aggregates first responses, so its
        # success ns/count is the average time-to-first-response).
        self.response_stats: Dict[str, Dict[str, List[int]]] = {}

    def record(self, field_name: str, duration_ns: int) -> None:
        with self.lock:
            self.counts[field_name] += 1
            self.ns[field_name] += duration_ns
        if field_name == "fail" and self._metrics is not None:
            self._metrics.observe_failure(self._model)

    def record_success(
        self, batch: int, queue_ns, in_ns, infer_ns, out_ns,
        executions: int = 1, trace_id: str = "",
    ):
        """Account one successful request. ``executions`` is 0 for requests
        that shared a dynamically-batched model execution with an earlier
        request in the same batch (Triton semantics: inference_count counts
        requests/rows, execution_count counts device executions).
        ``trace_id`` (traced requests only) rides to the metrics hook as
        the duration histogram's OpenMetrics exemplar."""
        now_ms = int(time.time() * 1000)
        total = queue_ns + in_ns + infer_ns + out_ns
        with self.lock:
            self.inference_count += batch
            self.execution_count += executions
            self.last_inference = now_ms
            for f, ns in (
                ("success", total),
                ("queue", queue_ns),
                ("compute_input", in_ns),
                ("compute_infer", infer_ns),
                ("compute_output", out_ns),
            ):
                self.counts[f] += 1
                self.ns[f] += ns
        if self._metrics is not None:
            self._metrics.observe_success(
                self._model, queue_ns, in_ns + infer_ns + out_ns, total,
                trace_id=trace_id,
            )

    def record_success_batch(
        self,
        n_requests: int,
        rows: int,
        queue_ns_total: int,
        infer_ns_total: int,
        out_ns_total: int,
        executions: int = 1,
    ) -> None:
        """Account ``n_requests`` successful requests of one merged
        execution with a single lock acquisition (the direct path runs
        this per chunk instead of record_success per request)."""
        now_ms = int(time.time() * 1000)
        total = queue_ns_total + infer_ns_total + out_ns_total
        with self.lock:
            self.inference_count += rows
            self.execution_count += executions
            self.last_inference = now_ms
            for f, ns in (
                ("success", total),
                ("queue", queue_ns_total),
                ("compute_input", 0),
                ("compute_infer", infer_ns_total),
                ("compute_output", out_ns_total),
            ):
                self.counts[f] += n_requests
                self.ns[f] += ns
        if self._metrics is not None and n_requests:
            # per-request averages of the chunk totals, booked n at once
            self._metrics.observe_success(
                self._model,
                queue_ns_total // n_requests,
                (infer_ns_total + out_ns_total) // n_requests,
                total // n_requests,
                count=n_requests,
            )

    def record_execution(self) -> None:
        """Count a device execution whose every request failed packaging."""
        with self.lock:
            self.execution_count += 1

    RESPONSE_FIELDS = (
        "success",
        "fail",
        "cancel",
        "compute_infer",
        "compute_output",
        "empty_response",
    )

    def record_response(
        self,
        index: int,
        infer_ns: int,
        out_ns: int,
        latency_ns: int,
        empty: bool,
    ) -> None:
        """Account one decoupled response (Triton response_stats shape):
        ``infer_ns`` = model time since the previous response, ``out_ns`` =
        packaging, ``latency_ns`` = cumulative since request start."""
        with self.lock:
            entry = self.response_stats.setdefault(
                str(index), {f: [0, 0] for f in self.RESPONSE_FIELDS}
            )
            if empty:
                # Disjoint categories (Triton semantics): an empty response
                # is not a success and carries no compute samples.
                entry["empty_response"][0] += 1
                entry["empty_response"][1] += latency_ns
                return
            entry["success"][0] += 1
            entry["success"][1] += latency_ns
            entry["compute_infer"][0] += 1
            entry["compute_infer"][1] += infer_ns
            entry["compute_output"][0] += 1
            entry["compute_output"][1] += out_ns

    def record_response_failure(
        self, index: int, latency_ns: int, cancelled: bool = False
    ) -> None:
        """Account a response slot that errored (or was cancelled) mid-stream
        — the per-response twin of the aggregate 'fail' field, mirroring the
        fail/cancel entries of Triton's InferResponseStatistics."""
        with self.lock:
            entry = self.response_stats.setdefault(
                str(index), {f: [0, 0] for f in self.RESPONSE_FIELDS}
            )
            key = "cancel" if cancelled else "fail"
            entry[key][0] += 1
            entry[key][1] += latency_ns

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            snap = {
                "inference_count": self.inference_count,
                "execution_count": self.execution_count,
                "last_inference": self.last_inference,
                "inference_stats": {
                    f: {"count": self.counts[f], "ns": self.ns[f]}
                    for f in self.FIELDS
                },
            }
            if self.response_stats:
                # Decoupled per-response statistics (Triton response_stats
                # wire shape). The reference's client-side stats treat a
                # stream as one opaque request — its own known blind spot
                # (grpc_client.cc:1650-1653); don't inherit that.
                snap["response_stats"] = {
                    key: {
                        f: {"count": v[0], "ns": v[1]}
                        for f, v in fields.items()
                    }
                    for key, fields in self.response_stats.items()
                }
            return snap


def _to_host(raw: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Materialize model outputs on host with ONE batched transfer.

    Per-array ``np.asarray`` readbacks of device results are the dominant
    cost on TPU relays (~tens of ms each); ``jax.device_get`` of the whole
    dict issues a single batched transfer. Models that already return numpy
    pass through untouched. Runs inside the executor thread so the event
    loop never blocks on a device round-trip.
    """
    if all(isinstance(v, np.ndarray) for v in raw.values()):
        return raw
    try:
        import jax

        raw = jax.device_get(raw)
    except Exception:  # noqa: BLE001 - fall back to per-array conversion
        pass
    return {k: np.asarray(v) for k, v in raw.items()}


class _BatchMeta:
    """Per-model caches + pure helpers shared by the two dynamic-batching
    paths (the event-loop :class:`_ModelBatcher` and the synchronous
    :meth:`ServerCore.infer_direct` used by the native front-end's pump
    thread). Read-only after construction, so cross-thread use is safe."""

    def __init__(self, model: Model):
        self.model = model
        self.declared = {i["name"] for i in model.inputs}
        self.declared_shapes = {
            i["name"]: list(i["shape"]) for i in model.inputs
        }
        self.ragged = bool(getattr(model, "allow_ragged_batch", False))

    def validate(self, request: CoreRequest) -> int:
        """Batch-path request validation; returns the request's row count.

        Happens per request so a malformed request fails alone instead of
        poisoning the batch it would have joined.
        """
        model = self.model
        declared = self.declared
        rows = 1
        if request.inputs:
            rows = int(request.inputs[0].shape[0]) if request.inputs[0].shape else 1
            for t in request.inputs:
                if declared and t.name not in declared:
                    raise InferenceServerException(
                        f"unexpected inference input '{t.name}' for model "
                        f"'{model.name}'"
                    )
                if not t.shape or int(t.shape[0]) != rows:
                    raise InferenceServerException(
                        f"all inputs must share the batch dimension: input "
                        f"'{t.name}' shape {list(t.shape)} does not match "
                        f"batch size {rows}"
                    )
            if rows > model.max_batch_size:
                raise InferenceServerException(
                    f"inference request batch-size must be <= "
                    f"{model.max_batch_size} for '{model.name}', got {rows}"
                )
        return rows

    @staticmethod
    def _signature_params(parameters: Dict[str, Any]) -> str:
        """Parameter part of the batch-compat signature. Scheduling
        params (priority/timeout) are admission inputs, not execution
        inputs — two same-shape requests that differ only in them must
        still share a batch, so they are excluded here."""
        if not parameters:
            return ""
        filtered = [
            (k, v)
            for k, v in sorted(parameters.items())
            if k not in SCHEDULING_PARAM_KEYS
        ]
        return repr(filtered) if filtered else ""

    def signature(self, request: CoreRequest):
        if not self.ragged:
            return (
                tuple(
                    (t.name, t.datatype, tuple(t.shape[1:]))
                    for t in request.inputs
                ),
                self._signature_params(request.parameters),
            )
        sig = []
        for t in request.inputs:
            declared = self.declared_shapes.get(t.name)
            dims = tuple(t.shape[1:])
            if declared is not None and len(declared) == len(dims):
                # Drop ragged (-1) dims: they merge via padding. The rank
                # stays in the signature so a wrong-rank request can never
                # share (and poison) a well-formed batch.
                dims = tuple(
                    d for d, dd in zip(dims, declared) if dd != -1
                )
            sig.append((t.name, t.datatype, len(t.shape), dims))
        return (
            tuple(sig),
            self._signature_params(request.parameters),
        )

    def pad_ragged(self, name: str, arrays: List[np.ndarray]) -> List[np.ndarray]:
        """Zero-pad the -1-declared dims of `arrays` to a shared
        power-of-two bucket so they concatenate along axis 0."""
        from client_tpu.server.models import pad_batch_bucket

        declared = self.declared_shapes.get(name)
        rank = arrays[0].ndim
        if declared is None or len(declared) != rank - 1:
            return arrays
        cap = getattr(self.model, "ragged_dim_cap", None)
        targets = []
        for ax in range(1, rank):
            if declared[ax - 1] == -1:
                bucket = pad_batch_bucket(max(a.shape[ax] for a in arrays))
                if cap is not None:
                    # The bucket must not exceed the model's hard limit: a
                    # batch of individually-valid requests would otherwise
                    # be rejected wholesale (cap >= every member, so the
                    # clamped bucket still covers the batch).
                    bucket = min(bucket, cap)
                targets.append(bucket)
            else:
                targets.append(arrays[0].shape[ax])
        out = []
        pad_value = getattr(self.model, "ragged_pad_value", 0)
        for a in arrays:
            pads = [(0, 0)] + [
                (0, targets[ax - 1] - a.shape[ax]) for ax in range(1, rank)
            ]
            if any(p[1] for p in pads):
                a = np.pad(a, pads, constant_values=pad_value)
            out.append(a)
        return out

    def merge_inputs(self, requests: List[CoreRequest]) -> Dict[str, np.ndarray]:
        """Concatenate the batch's inputs along axis 0 (ragged dims padded)."""
        if len(requests) == 1:
            return {t.name: t.data for t in requests[0].inputs}
        merged: Dict[str, np.ndarray] = {}
        for pos, t in enumerate(requests[0].inputs):
            name = t.name
            arrays = []
            for r in requests:
                # Same-position fast path: clients nearly always order
                # inputs identically (the signature guarantees the same
                # input SET, not order).
                cand = r.inputs[pos]
                if cand.name != name:
                    cand = next(i for i in r.inputs if i.name == name)
                arrays.append(cand.data)
            if self.ragged:
                arrays = self.pad_ragged(name, arrays)
            merged[name] = np.concatenate(arrays, axis=0)
        return merged


class _ModelBatcher:
    """Serial dynamic batcher (the server-side analogue of Triton's
    ``dynamic_batching`` scheduler).

    While one batch executes on device, newly arriving requests queue; the
    next batch takes everything compatible that is pending, up to
    ``max_batch_size`` rows. The execution time itself is the accumulation
    window — no artificial delay — so a lone request sees no added latency
    while concurrent load amortizes the device round-trip (which on TPU
    relays has a large flat per-trip cost; see VERDICT r1 / PERF.md).

    Requests are compatible when their input signature matches: same input
    names, datatypes, non-batch dims, and parameters. Incompatible requests
    wait for a batch of their own, preserving arrival order per signature.

    Models with ``allow_ragged_batch`` relax the shape part of the
    signature: dims declared -1 are excluded, and at merge time those dims
    are zero-padded to a shared power-of-two bucket (Triton's ragged
    batching, server-side) — so concurrent BERT/LLM requests of different
    sequence lengths share one device execution.

    Admission control (client_tpu.scheduling): the pending list is a
    bounded multi-level :class:`PriorityQueue` — ``submit()`` rejects
    with 429/RESOURCE_EXHAUSTED once ``max_queue_size`` requests wait,
    ``_take_batch`` consumes in (priority, arrival) order, and entries
    whose queue deadline passes fail with a deadline error before
    execution (or are demoted behind in-deadline work when the model's
    ``timeout_action`` is "continue").
    """

    def __init__(self, core: "ServerCore", model: Model):
        self.core = core
        self.model = model
        self.meta = core._batch_meta(model)
        self.policy = core._queue_policy(model)
        # queued entries: (request, future, signature, rows, arrival_ns)
        self.pending = PriorityQueue(levels=self.policy.levels)
        self.running = False

    def submit(self, request: CoreRequest) -> "asyncio.Future[CoreResponse]":
        """Validate + enqueue a request; returns a future for its response.

        Raises :class:`QueueFullError` (already booked on metrics/stats)
        when the queue is at ``max_queue_size``."""
        rows = self.meta.validate(request)
        policy = self.policy
        if (
            policy.max_queue_size
            and len(self.pending) >= policy.max_queue_size
        ):
            error = QueueFullError(self.model.name, policy.max_queue_size)
            self.core._book_rejection(
                self.model.name, request, error, record_fail=True
            )
            raise error
        arrival_ns = time.monotonic_ns()
        policy.stamp(request, arrival_ns)
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        self.pending.push(
            (request, future, self.meta.signature(request), rows, arrival_ns),
            level=request.priority_level,
            deadline_ns=request.deadline_ns,
            timeout_action=policy.timeout_action,
        )
        self._publish_depths()
        if not self.running:
            self.running = True
            loop.create_task(self._drain())
        return future

    async def _drain(self) -> None:
        try:
            while len(self.pending):
                self._expire_pending()
                if not len(self.pending):
                    break
                batch = self._take_batch()
                resources = self.policy.rate_resources
                if resources:
                    await self.core.rate_limiter.acquire(
                        resources, self.policy.rate_priority
                    )
                    try:
                        # the grant wait may have outlived queue
                        # deadlines: reject-action entries still fail
                        # BEFORE execution, as the policy promises
                        batch = self._expire_taken(batch)
                        if batch:
                            await self._execute_batch(batch)
                    finally:
                        self.core.rate_limiter.release(resources)
                else:
                    await self._execute_batch(batch)
        finally:
            self.running = False
            if len(self.pending):  # raced with a submit after the check
                self.running = True
                asyncio.get_running_loop().create_task(self._drain())

    def _reject_expired(self, entry, now_ns: int) -> None:
        """Fail one (request, future, ...) entry with a deadline error."""
        request, future, _sig, _rows, arrival_ns = entry
        error = QueueTimeoutError(
            self.model.name, self.policy.timeout_us_of(request.parameters)
        )
        self.core._book_rejection(
            self.model.name,
            request,
            error,
            record_fail=True,
            latency_ns=now_ns - arrival_ns,
        )
        if not future.done():
            future.set_exception(error)

    def _expire_pending(self) -> None:
        """Fail queued entries whose deadline passed (reject action);
        "continue" entries were demoted inside the queue instead."""
        now_ns = time.monotonic_ns()
        expired = self.pending.expire(now_ns)
        for item in expired:
            self._reject_expired(item.value, now_ns)
        if expired:
            self._publish_depths()

    def _expire_taken(self, entries: List[Any]) -> List[Any]:
        """Deadline re-check for a batch already popped from the queue
        (the rate-limiter grant wait sits between take and execute);
        returns the still-live entries."""
        if self.policy.timeout_action != TIMEOUT_ACTION_REJECT:
            return entries
        now_ns = time.monotonic_ns()
        live = []
        for entry in entries:
            deadline_ns = entry[0].deadline_ns
            if deadline_ns is not None and now_ns > deadline_ns:
                self._reject_expired(entry, now_ns)
            else:
                live.append(entry)
        return live

    def _take_batch(self) -> List[Any]:
        """Pop the highest-priority oldest request plus every compatible
        queued request, bounded by max_batch_size rows (submit() already
        rejected any single request exceeding the max). The scan walks
        the queue in (priority, arrival) order and stops taking a
        signature at its first entry that does not fit the row budget, so
        arrival order within a (priority, signature) lane is preserved."""
        items = self.pending.scan()
        signature = items[0].value[2]
        budget = self.model.max_batch_size
        taken_items, taken, rows = [], [], 0
        signature_full = False
        for item in items:
            entry = item.value
            if (
                entry[2] == signature
                and not signature_full
                and rows + entry[3] <= budget
            ):
                taken_items.append(item)
                taken.append(entry)
                rows += entry[3]
            elif entry[2] == signature:
                signature_full = True
        self.pending.remove(taken_items)
        self._publish_depths()
        return taken

    def _publish_depths(self) -> None:
        self.core.metrics.set_queue_depth(
            self.model.name, self.pending.depths()
        )

    async def _execute_batch(self, entries: List[Any]) -> None:
        loop = asyncio.get_running_loop()
        model, core = self.model, self.core
        stats = core._stats_for(model.name)
        prof = core.profiling
        exec_start = time.monotonic_ns()
        requests = [e[0] for e in entries]
        n = len(entries)
        # one take() decision covers the whole batch's stage brackets
        measured = prof.take()
        try:
            if measured:
                # queue_wait is a wall phenomenon (no thread attached):
                # CPU books 0, the wall total is the batch's queued ns
                prof.account(
                    "queue_wait",
                    0,
                    wall_ns=sum(exec_start - e[4] for e in entries),
                    count=n,
                )
                a0 = prof.cpu_now()
                merged = self.meta.merge_inputs(requests)
                prof.account("batch_assembly", prof.cpu_now() - a0, count=n)
            else:
                merged = self.meta.merge_inputs(requests)

            def _run():
                # compute vs readback split on the executor thread (its
                # own thread-CPU clock — exactly the CPU this stage burnt)
                with model.placement():
                    if not measured:
                        return _to_host(
                            model.execute(merged, requests[0].parameters)
                        )
                    c0 = prof.cpu_now()
                    raw = model.execute(merged, requests[0].parameters)
                    c1 = prof.cpu_now()
                    host = _to_host(raw)
                    c2 = prof.cpu_now()
                    prof.account("compute", c1 - c0, count=n)
                    prof.account("readback", c2 - c1, count=n)
                    return host

            raw = await loop.run_in_executor(core._executor, _run)
            infer_end = time.monotonic_ns()
            core.add_busy_ns(model, infer_end - exec_start)
            core.metrics.observe_execution(
                model.name, sum(e[3] for e in entries)
            )
        except Exception as e:  # noqa: BLE001 - fail every request in batch
            # the only trace this previously left was N client error
            # responses — record the server-side evidence too
            core._log_request_error(
                "batch_execution_failed", model.name, e, path="batch"
            )
            now = time.monotonic_ns()
            for req, future, _sig, _rows, arrival in entries:
                stats.record("fail", now - arrival)
                core._record_exemplar(
                    model.name,
                    req,
                    path="batch",
                    status="error",
                    error=str(e),
                    arrival_ns=arrival,
                    exec_start_ns=exec_start,
                    end_ns=now,
                )
                if not future.done():
                    future.set_exception(e)
            return
        offset = 0
        # The ONE device execution is credited to the first request whose
        # packaging succeeds; if every request fails packaging it is still
        # counted (the execution happened regardless).
        execution_pending = 1
        for request, future, _sig, rows, arrival in entries:
            try:
                if len(entries) == 1:
                    sliced = raw
                else:
                    sliced = {k: v[offset : offset + rows] for k, v in raw.items()}
                response = core._package_profiled(model, request, sliced)
                out_end = time.monotonic_ns()
                stats.record_success(
                    rows,
                    queue_ns=exec_start - arrival,
                    in_ns=0,
                    infer_ns=infer_end - exec_start,
                    out_ns=out_end - infer_end,
                    executions=execution_pending,
                    trace_id=_trace_id_of(request),
                )
                _trace_stages(
                    request.trace, arrival, exec_start, infer_end, out_end
                )
                core._record_exemplar(
                    model.name,
                    request,
                    path="batch",
                    arrival_ns=arrival,
                    exec_start_ns=exec_start,
                    infer_end_ns=infer_end,
                    end_ns=out_end,
                    rows=rows,
                )
                execution_pending = 0
                if not future.done():
                    future.set_result(response)
            except Exception as e:  # noqa: BLE001 - per-request packaging error
                core._log_request_error(
                    "packaging_failed", model.name, e, path="batch"
                )
                now = time.monotonic_ns()
                stats.record("fail", now - arrival)
                core._record_exemplar(
                    model.name,
                    request,
                    path="batch",
                    status="error",
                    error=str(e),
                    arrival_ns=arrival,
                    exec_start_ns=exec_start,
                    infer_end_ns=infer_end,
                    end_ns=now,
                    rows=rows,
                )
                if not future.done():
                    future.set_exception(e)
            offset += rows
        if execution_pending:
            stats.record_execution()


class ServerCore:
    """The protocol-independent inference engine."""

    def __init__(
        self,
        repository: Optional[ModelRepository] = None,
        max_workers: int = 32,
        logger=None,
        flight_recorder=None,
    ):
        self.repository = repository or ModelRepository()
        self.shm = SharedMemoryManager()
        self.stats: Dict[str, _Stats] = {}
        self._stats_lock = threading.Lock()
        self._batchers: Dict[str, _ModelBatcher] = {}
        self._executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="client-tpu-exec"
        )
        self.live = True
        # The trace extension, made real: sampling, per-model settings,
        # timestamped records (observability.TraceManager). The old inert
        # trace_settings dict survives as a read-only property below.
        from client_tpu.observability.server import TraceManager

        self.trace_manager = TraceManager()
        # Execution grants against named resource pools (ModelRateLimiter
        # semantics); models that declare rate_limiter resources acquire
        # them around every device execution.
        self.rate_limiter = RateLimiter()
        # Cumulative device-busy nanoseconds (device-placed executions
        # only) — the monotone counter scrapers derive duty cycle from.
        # Owned here, not by an HTTP handler, so every front-end and any
        # number of concurrent scrapers see one consistent time base.
        self._busy_lock = threading.Lock()
        self._device_busy_ns = 0
        # per-device split of the same counter: sharded models credit
        # every device of their mesh, plain models their default device —
        # the source of tpu_device_compute_ns_total{device} and the
        # per-chip duty/skew view
        self._device_busy: Dict[str, int] = {}
        self._default_device_label: Optional[str] = None
        from client_tpu.server.metrics import ServerMetrics

        self.metrics = ServerMetrics(self)
        # Fixed-layout shm rings over registered regions (server.shm_ring):
        # validated lazily per registration, cached per region object.
        from client_tpu.server.shm_ring import RingRegistry

        self.shm_rings = RingRegistry(self.shm, metrics=self.metrics)
        # Per-stage thread-CPU accounting (observability.profiling):
        # default-off; while disabled every stage event is one attribute
        # check. Enabled via POST /v2/debug/profiling (the perf
        # harness's --profile-server does this for the run's duration).
        from client_tpu.observability.profiling import StageCpuAccounting

        self.profiling = StageCpuAccounting(
            metrics_hook=self.metrics.observe_stage_cpu
        )
        # Graceful lifecycle: SERVING -> DRAINING -> STOPPED state plus
        # the in-flight census every execution path reports into, so a
        # drain can WAIT for work instead of cancelling it.
        self.lifecycle = DrainController()
        # The logging extension, made real (observability.logging): the
        # /v2/logging settings live inside the logger and gate what it
        # emits — toggling them changes server output with no restart.
        from client_tpu.observability.logging import StructuredLogger
        from client_tpu.observability.recorder import FlightRecorder

        self.logger = logger if logger is not None else StructuredLogger(
            name="server"
        )
        # Per-request exemplars of recent/failed/slowest requests
        # (GET /v2/debug/requests). On by default — recording is a dict
        # build + lock + deque append; measured overhead in PERF.md.
        self.flight_recorder = (
            flight_recorder if flight_recorder is not None else FlightRecorder()
        )

    @property
    def trace_settings(self) -> Dict[str, Any]:
        """The effective global trace settings (compat view over the
        trace manager; update through ``trace_manager.update``)."""
        return self.trace_manager.settings()

    @property
    def log_settings(self) -> Dict[str, Any]:
        """The effective global log settings (compat view over the
        structured logger; update through :meth:`update_log_settings`)."""
        return self.logger.settings()

    def update_log_settings(
        self, updates: Dict[str, Any], model_name: str = ""
    ) -> Dict[str, Any]:
        """Validated /v2/logging update (per-model override when
        ``model_name`` is set); returns the effective settings."""
        return self.logger.update(updates, model_name)

    def _shutdown_model_hooks(self) -> None:
        """Stop model-owned background machinery (the LLM engine's step
        loop): invoked on the serving loop at the end of a drain, and
        again (idempotently) from close() for cores that never drain."""
        for entry in self.repository.index():
            model = self.repository.peek(entry["name"])
            shutdown = getattr(model, "shutdown", None)
            if shutdown is not None:
                try:
                    shutdown()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    pass

    def close(self) -> None:
        self.lifecycle.mark_stopped()
        self._shutdown_model_hooks()
        self._executor.shutdown(wait=False, cancel_futures=True)
        self.trace_manager.close()
        self.logger.close()

    # -- graceful lifecycle --------------------------------------------------

    @property
    def ready(self) -> bool:
        """Readiness as load balancers should see it: live, accepting
        (not draining), and the repository's ready set non-degraded.
        Liveness (:attr:`live`) deliberately stays true through a drain."""
        return (
            self.live
            and self.lifecycle.accepting
            and not self.repository.degraded()
        )

    @property
    def recovering(self) -> bool:
        """True while any loaded model's engine reload is in flight
        (surfaced in ``debug_state()`` and overlaid on the
        ``tpu_server_state`` gauge; readiness is NOT dropped — the
        replica keeps serving its healthy models and answers the
        quarantined one with retryable 503s)."""
        for entry in self.repository.index():
            try:
                model = self.repository.peek(entry["name"])
            except Exception:  # noqa: BLE001 - introspection best-effort
                continue
            if getattr(model, "recovering", False):
                return True
        return False

    def _lifecycle_admit(self, model_name: str, trace=None) -> None:
        """Drain gate + in-flight tracking for one request; books the
        rejection counter and the trace event when draining."""
        try:
            self.lifecycle.admit(model_name)
        except ServerDrainingError:
            self.metrics.observe_drain_rejection(model_name)
            if trace is not None:
                trace.event("DRAIN_REJECTED")
            raise

    def reject_if_draining(self, model_name: str = "") -> None:
        """Front-end fast path: raise the drain rejection before paying
        request decode cost. Books exactly like an admission rejection
        (check() never touches the in-flight census)."""
        try:
            self.lifecycle.check()
        except ServerDrainingError:
            self.metrics.observe_drain_rejection(model_name)
            raise

    async def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful shutdown sequence (runs on the serving loop):
        stop admitting, wait for in-flight + queued work up to
        ``timeout_s``, then fail anything still queued with a clean
        503/UNAVAILABLE (never a cancelled future). Returns True when
        everything drained inside the deadline."""
        self.lifecycle.begin_drain()
        self.logger.info(
            "drain_started",
            timeout_s=timeout_s,
            inflight=self.lifecycle.inflight(),
        )
        drained = await self.lifecycle.wait_idle(timeout_s)
        if not drained:
            failed = self.fail_pending()
            self.logger.warning(
                "drain_deadline_expired", failed_pending=failed
            )
            # the failed futures' awaiters need a tick to observe before
            # the front-ends close under them (deliberately NOT folded
            # into the return value: the deadline DID expire)
            await self.lifecycle.wait_idle(min(1.0, timeout_s or 1.0))
        self.lifecycle.mark_stopped()
        # runs ON the serving loop: model background tasks (engine step
        # loops) cancel cleanly here, before the loop itself closes
        self._shutdown_model_hooks()
        self.logger.info("drain_completed", drained=drained)
        return drained

    def fail_pending(self, model_name: Optional[str] = None) -> int:
        """Fail every queued (not yet executing) batcher entry with a
        drain rejection — the past-deadline counterpart of waiting.
        Loop-thread only (the futures belong to the serving loop)."""
        failed = 0
        for name, batcher in list(self._batchers.items()):
            if model_name is not None and name != model_name:
                continue
            items = batcher.pending.scan()
            if not items:
                continue
            batcher.pending.remove(items)
            batcher._publish_depths()
            for item in items:
                _request, future, _sig, _rows, _arrival = item.value
                self.metrics.observe_drain_rejection(name)
                if not future.done():
                    future.set_exception(
                        ServerDrainingError(
                            self.lifecycle.state,
                            retry_after_s=self.lifecycle.retry_after_s,
                        )
                    )
                failed += 1
        return failed

    def load_model(
        self, name: str, config_override: Optional[str] = None
    ) -> None:
        """Repository load plus the telemetry bookkeeping every load
        path needs: the model's live-telemetry state is reset so the
        next record re-resolves the freshly-loaded slo declaration.
        Front-ends and the in-process backend all load through here."""
        self.repository.load(name, config_override=config_override)
        self.metrics.telemetry.reset(name)
        self.logger.info("model_loaded", model=name)

    def unload_model(self, name: str, drain_timeout_s: float = 5.0):
        """Repository unload with real per-model lifecycle: the model
        stops admitting immediately (503/UNAVAILABLE), queued and
        in-flight work drains in the background, then the batcher state
        is evicted and the index entry flips to UNAVAILABLE/"unloaded".

        Returns the finalization task when a loop is running (callers on
        the serving loop — both front-ends — never block on the drain),
        else finalizes synchronously.
        """
        old_model = self.repository.peek(name)
        epoch = self.repository.unload(name)
        # drop the model's live-telemetry state: the rolling windows
        # describe the outgoing instance, and a later load must
        # re-resolve the repository's (possibly changed) slo declaration
        self.metrics.telemetry.reset(name)
        self.logger.info(
            "model_unloading",
            model=name,
            inflight=self.lifecycle.inflight(name),
        )
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is None:
            self._evict_batcher(name, old_model)
            self.repository.finish_unload(name, epoch)
            # in-flight completions between the reset above and here
            # re-create telemetry state for the dead model; this final
            # reset is the one collect() prunes gauges against
            self.metrics.telemetry.reset(name)
            return None
        return loop.create_task(
            self._finalize_unload(name, old_model, epoch, drain_timeout_s)
        )

    async def _finalize_unload(
        self, name: str, old_model, epoch: int, drain_timeout_s: float
    ) -> None:
        drained = await self.lifecycle.wait_idle(
            drain_timeout_s, model_name=name
        )
        if self.repository.epoch_of(name) != epoch:
            # a load() superseded this unload mid-drain (the rolling
            # restart pattern): the census now counts the NEW model's
            # traffic — failing its queued work here would drop the very
            # requests the reload exists to keep serving
            return
        if not drained:
            # past the drain deadline: queued entries fail cleanly
            self.fail_pending(name)
        self._evict_batcher(name, old_model)
        self.repository.finish_unload(name, epoch)
        # requests that completed during the drain re-created telemetry
        # state for the outgoing model (observe_success -> record); this
        # final reset — epoch-guarded above, so a superseding load's
        # traffic is never dropped — leaves nothing for collect() to
        # keep exporting
        self.metrics.telemetry.reset(name)
        self.logger.info("model_unloaded", model=name, drained=drained)

    def _evict_batcher(self, name: str, model=None) -> None:
        """Drop a model's batcher state if it still belongs to the
        unloaded model object and holds no queued work (a reload may
        already have installed a new batcher — leave that one alone)."""
        batcher = self._batchers.get(name)
        if batcher is None:
            return
        if model is not None and batcher.model is not model:
            return
        if len(batcher.pending):
            return
        self._batchers.pop(name, None)

    def _stats_for(self, model_name: str) -> _Stats:
        with self._stats_lock:
            if model_name not in self.stats:
                self.stats[model_name] = _Stats(
                    metrics=self.metrics, model_name=model_name
                )
            return self.stats[model_name]

    # -- flight recorder / structured logging --------------------------------

    def _record_exemplar(
        self,
        model_name: str,
        request: CoreRequest,
        path: str,
        status: str = "ok",
        error: str = "",
        arrival_ns: int = 0,
        exec_start_ns: Optional[int] = None,
        infer_end_ns: Optional[int] = None,
        end_ns: Optional[int] = None,
        rows: int = 1,
        responses: Optional[int] = None,
    ) -> None:
        """Book one completed request into the flight recorder. Stage
        boundaries are the same monotonic reads the statistics extension
        books (queue = arrival->exec, compute = exec->infer_end, package
        = infer_end->end), so exemplars and aggregates always agree."""
        if end_ns is None:
            end_ns = time.monotonic_ns()
        exec_start = exec_start_ns if exec_start_ns is not None else end_ns
        infer_end = infer_end_ns if infer_end_ns is not None else exec_start
        trace = request.trace
        self.flight_recorder.record(
            model_name,
            request_id=request.id,
            trace_id=trace.trace_id if trace is not None else "",
            status=status,
            error=error,
            path=path,
            queue_us=(exec_start - arrival_ns) / 1e3 if arrival_ns else 0.0,
            compute_us=(infer_end - exec_start) / 1e3,
            package_us=(end_ns - infer_end) / 1e3,
            total_us=(
                (end_ns - arrival_ns) if arrival_ns else (end_ns - exec_start)
            )
            / 1e3,
            rows=rows,
            priority=request.priority_level,
            responses=responses,
        )

    def _log_request_error(
        self, event: str, model_name: str, exc: BaseException, path: str
    ) -> None:
        """Server-side record for an execution/packaging failure that is
        otherwise only converted into a client response. Rate-limited per
        (event, model): a model bug failing every request leaves a
        traceback trail without melting the log sink."""
        self.logger.error(
            event,
            model=model_name,
            exc=exc,
            rate_key=(event, model_name),
            path=path,
        )

    # -- device busy accounting (duty cycle) --------------------------------

    def add_busy_ns(self, model: Model, duration_ns: int) -> None:
        """Credit one device execution's nanoseconds to the busy counter.
        Host-placed models (device == "cpu") never count — they execute on
        the host and must not report the TPU as busy.

        The same duration also books per device: a sharded model's SPMD
        program runs on every device of its mesh in lockstep, so each
        mesh device is credited the execution's wall time; unsharded
        models credit their (single) default device. This is the one
        seam all four execution paths already pass through, so per-device
        accounting needs no per-path wiring."""
        if getattr(model, "device", "") == "cpu":
            return
        labels = self._device_labels_for(model)
        with self._busy_lock:
            self._device_busy_ns += duration_ns
            busy = self._device_busy
            for label in labels:
                busy[label] = busy.get(label, 0) + duration_ns

    def _device_labels_for(self, model: Model) -> tuple:
        """The metric labels of the devices this model executes on
        (cached on the model object; a reload rebuilds it)."""
        labels = getattr(model, "_ctpu_device_labels", None)
        if labels is None:
            plan = getattr(model, "mesh_plan", None)
            if plan is not None:
                labels = plan.device_labels
            else:
                labels = (self._default_device_label_value(),)
            model._ctpu_device_labels = labels
        return labels

    def _default_device_label_value(self) -> str:
        if self._default_device_label is None:
            try:
                import jax

                self._default_device_label = str(jax.devices()[0].id)
            except Exception:  # noqa: BLE001 - no backend available
                self._default_device_label = "0"
        return self._default_device_label

    @property
    def device_busy_ns_total(self) -> int:
        with self._busy_lock:
            return self._device_busy_ns

    def device_busy_by_device(self) -> Dict[str, int]:
        """Cumulative busy nanoseconds per device label (monotone; empty
        until the first device execution)."""
        with self._busy_lock:
            return dict(self._device_busy)

    def _batch_meta(self, model: Model) -> _BatchMeta:
        """Per-model batching caches, shared by both batching paths.
        Cached on the model object so a repository reload invalidates it."""
        meta = getattr(model, "_ctpu_batch_meta", None)
        if meta is None or meta.model is not model:
            meta = _BatchMeta(model)
            model._ctpu_batch_meta = meta
        return meta

    # -- scheduling / admission control --------------------------------------

    def _queue_policy(self, model: Model) -> QueuePolicy:
        """The model's resolved admission policy (cached on the model so
        a repository reload rebuilds it). First resolution registers the
        model's rate-limiter demands with the shared pool."""
        policy = getattr(model, "_ctpu_queue_policy", None)
        if policy is None or policy.model is not model:
            policy = QueuePolicy.from_model(model)
            model._ctpu_queue_policy = policy
            if policy.rate_resources:
                self.rate_limiter.register(policy.rate_resources)
        return policy

    def _admission_for(self, model: Model) -> AdmissionGate:
        """Waiting-room gate for the non-batcher execution paths."""
        gate = getattr(model, "_ctpu_admission_gate", None)
        if gate is None or gate.policy.model is not model:
            gate = AdmissionGate(self._queue_policy(model))
            model._ctpu_admission_gate = gate
        return gate

    def _book_rejection(
        self,
        model_name: str,
        request: CoreRequest,
        error: SchedulingError,
        record_fail: bool = False,
        latency_ns: int = 0,
    ) -> None:
        """Account one admission rejection everywhere it is observable:
        the dedicated reject counter (by reason), the trace record, and —
        when no other error path will — the statistics 'fail' field."""
        self.metrics.observe_rejection(model_name, error.reason)
        if request.trace is not None:
            request.trace.event("QUEUE_REJECTED")
        if record_fail:
            self._stats_for(model_name).record("fail", latency_ns)
        now_ns = time.monotonic_ns()
        self._record_exemplar(
            model_name,
            request,
            path="admission",
            status="rejected",
            error=error.message(),
            arrival_ns=now_ns - latency_ns,
            exec_start_ns=now_ns,
            end_ns=now_ns,
        )
        self.logger.verbose(
            "request_rejected",
            model=model_name,
            reason=error.reason,
            request_id=request.id,
        )

    def _admit_single(self, model: Model, request: CoreRequest):
        """Admission for the non-batcher paths: stamps the scheduling
        fields and claims a waiting-room slot. Returns the gate ticket
        (``started()`` releases the slot when execution begins), or None
        on the fast path — an unconfigured model and a request with no
        parameters have nothing to schedule, so the stamp and the gate
        lock are skipped entirely. Raises :class:`QueueFullError` —
        already booked — when the room is full."""
        policy = self._queue_policy(model)
        if not policy.enabled and not request.parameters:
            return None
        policy.stamp(request, time.monotonic_ns())
        gate = self._admission_for(model)
        try:
            return gate.enter(model.name)
        except SchedulingError as e:
            self._book_rejection(model.name, request, e, record_fail=True)
            raise

    def _check_deadline(self, model: Model, request: CoreRequest) -> None:
        """Fail a request whose queue deadline passed before execution
        (reject action only; "continue" executes late)."""
        if (
            request.deadline_ns is not None
            and time.monotonic_ns() > request.deadline_ns
        ):
            policy = self._queue_policy(model)
            if policy.timeout_action == TIMEOUT_ACTION_REJECT:
                error = QueueTimeoutError(
                    model.name, policy.timeout_us_of(request.parameters)
                )
                # Fully booked here; generic error paths skip stats
                # accounting for SchedulingError to avoid double counts.
                self._book_rejection(
                    model.name, request, error, record_fail=True
                )
                raise error

    def _run_single(self, model: Model, request: CoreRequest, ticket=None):
        """Executor-side entry for the single path: leave the waiting
        room, enforce the queue deadline, then run the model. NEVER
        blocks on the rate limiter — a parked executor thread could
        starve the very execution whose release it waits for; limiter
        waits happen on the event loop (async path) or the caller's own
        pump thread (direct path) instead."""
        if ticket is not None:
            ticket.started()
        self._check_deadline(model, request)
        return self._run_model(model, request)

    # -- statistics API ------------------------------------------------------

    def statistics(self, model_name: str = "", model_version: str = ""):
        models = (
            [model_name]
            if model_name
            else [m["name"] for m in self.repository.index()]
        )
        result = []
        for name in models:
            try:
                model = self.repository.get(name)
            except InferenceServerException:
                if model_name:
                    raise
                continue
            snap = self._stats_for(name).snapshot()
            snap.update({"name": name, "version": model.version})
            result.append(snap)
        return {"model_stats": result}

    # -- device / mesh topology ----------------------------------------------

    def device_topology(self) -> Dict[str, Any]:
        """The ``devices`` block server metadata and ``debug_state()``
        serve: host platform + device inventory, and for every loaded
        model that resolved a mesh, which devices it occupies and how
        its tensors shard (plus the executor's cumulative
        device_put/compute/gather accounting when the model exposes
        one)."""
        try:
            import jax

            devices = jax.devices()
            from client_tpu.pod.runtime import pod_info

            # under jax.distributed the device list is GLOBAL — stamp
            # which process this report comes from so a pod member's
            # topology is distinguishable from a single-process replica
            # (and per-device, which member owns it)
            info: Dict[str, Any] = {
                "platform": devices[0].platform if devices else "unknown",
                "device_count": len(devices),
                **pod_info(),
                "devices": [
                    {
                        "id": d.id,
                        "kind": getattr(d, "device_kind", "") or d.platform,
                        "process": getattr(d, "process_index", 0),
                    }
                    for d in devices
                ],
            }
        except Exception as e:  # noqa: BLE001 - no backend available
            info = {
                "platform": "unavailable",
                "device_count": 0,
                "devices": [],
                "error": str(e),
            }
        models: Dict[str, Any] = {}
        for entry in self.repository.index():
            model = self.repository.peek(entry["name"])
            if model is None:
                continue
            plan = getattr(model, "mesh_plan", None)
            if plan is not None:
                doc = plan.describe()
                executor = getattr(model, "_executor", None)
                snapshot = getattr(executor, "snapshot", None)
                if snapshot is not None:
                    doc["executor"] = snapshot()
                models[entry["name"]] = doc
            elif isinstance(getattr(model, "mesh", None), dict):
                # declared but unresolved (e.g. load failed: mesh
                # requires N devices) — show what was asked for
                models[entry["name"]] = {
                    "axes": dict(model.mesh.get("axes", {})),
                    "resolved": False,
                    "reason": entry.get("reason", ""),
                }
        info["models"] = models
        return info

    # -- live-state introspection (GET /v2/debug/state) ----------------------

    def debug_state(self) -> Dict[str, Any]:
        """One snapshot of the server's live internals: what an operator
        asks a misbehaving replica before anything else. Each subsystem
        is captured under its own lock (a single consistent view per
        subsystem; cross-subsystem counts may be one request apart —
        taking one global lock across the hot path would cost more than
        the skew is worth)."""
        queues: Dict[str, Any] = {}
        for name, batcher in list(self._batchers.items()):
            queues[name] = {
                "depths": {
                    str(level): depth
                    for level, depth in batcher.pending.depths().items()
                },
                "max_queue_size": batcher.policy.max_queue_size,
            }
        # LLM engines: live continuous-batching/speculation counters per
        # engine-backed model (kv blocks, tokens-per-step, acceptance
        # rate) — the same document engine.stats() returns, so the debug
        # surface and the tests read one source of truth
        llm: Dict[str, Any] = {}
        for entry in self.repository.index():
            try:
                model = self.repository.peek(entry["name"])
            except Exception:  # noqa: BLE001 - introspection best-effort
                continue
            engine = getattr(model, "engine", None)
            stats = getattr(engine, "stats", None)
            if callable(stats):
                try:
                    doc = stats()
                    controller = getattr(model, "_recovery", None)
                    if controller is not None:
                        doc["recovery"] = controller.describe()
                    llm[entry["name"]] = doc
                except Exception:  # noqa: BLE001 - a broken engine must
                    continue  # not take down the debug surface
        return {
            "server": {
                "name": SERVER_NAME,
                "version": SERVER_VERSION,
                "live": self.live,
                "ready": self.ready,
                "recovering": self.recovering,
            },
            "llm": llm,
            "lifecycle": self.lifecycle.snapshot(),
            # device inventory + per-model mesh occupancy (which devices
            # a loaded sharded model runs on, and its executor's
            # cumulative device_put/compute/gather split)
            "devices": self.device_topology(),
            "queues": queues,
            "rate_limiter": self.rate_limiter.snapshot(),
            "models": self.repository.index(),
            "log_settings": self.logger.settings(),
            "log_model_overrides": self.logger.model_overrides(),
            "trace": {
                "settings": self.trace_manager.settings(),
                "started": self.trace_manager.started_count,
                "completed": self.trace_manager.completed_count,
            },
            "profiling": self.profiling.config(),
            "flight_recorder": self.flight_recorder.stats(),
            # compact live-telemetry block: shortest-window rolling p99 +
            # SLO burn per model (the full document is GET /v2/debug/slo)
            "slo": self.metrics.telemetry.summary(),
        }

    def debug_slo(self) -> Dict[str, Any]:
        """The ``GET /v2/debug/slo`` document: every tracked model's
        rolling latency windows (30s/5m p50/p95/p99 over the same bucket
        grid as ``/metrics``) plus error-budget status for models that
        declare an ``slo`` config."""
        return self.metrics.telemetry.snapshot()

    # -- inference -----------------------------------------------------------

    @staticmethod
    def _declared_ranks(model: Model) -> Dict[str, int]:
        """name -> declared rank, cached on the model (hot path)."""
        ranks = getattr(model, "_ctpu_declared_ranks", None)
        if ranks is None:
            ranks = {i["name"]: len(i["shape"]) for i in model.inputs}
            model._ctpu_declared_ranks = ranks
        return ranks

    @staticmethod
    def _has_batch_dim(model: Model, request: CoreRequest) -> bool:
        """True when the request's input shapes include the batch dim.

        Clients may send a batchable model its unbatched form (e.g. an
        [H, W, 3] image to a [-1, H, W, 3] model); those requests bypass
        the dynamic batcher — concatenating along axis 0 would corrupt
        them — and execute singly, as before batching existed. Only a
        request where EVERY declared input matches its unbatched rank
        counts; mixed-rank requests stay on the batcher path so its
        batch-dim validation rejects them. A model that declares no input
        metadata (or a request whose inputs match none of the declared
        names) gives nothing to compare ranks against — those requests
        execute singly rather than risking a concatenation along a dim 0
        that may not be a batch dim. For the same reason no max_batch_size
        check applies to them (dim 0 cannot be assumed to be a batch count),
        and they book inference_count 1 per request.
        """
        declared = ServerCore._declared_ranks(model)
        matches = [
            len(t.shape) == declared[t.name]
            for t in request.inputs
            if t.name in declared
        ]
        if not matches:
            return False
        return not all(matches)

    def _resolve_batch(self, model: Model, request: CoreRequest) -> int:
        if not request.inputs:
            return 1
        shape = request.inputs[0].shape
        if (
            model.max_batch_size > 0
            and shape
            and self._has_batch_dim(model, request)
        ):
            return int(shape[0])
        return 1

    def _run_model(
        self, model: Model, request: CoreRequest
    ) -> Dict[str, np.ndarray]:
        inputs = {t.name: t.data for t in request.inputs}
        declared = {i["name"] for i in model.inputs}
        for t in request.inputs:
            if declared and t.name not in declared:
                raise InferenceServerException(
                    f"unexpected inference input '{t.name}' for model "
                    f"'{model.name}'"
                )
        prof = self.profiling
        with model.placement():
            if not prof.take():
                return _to_host(model.execute(inputs, request.parameters))
            c0 = prof.cpu_now()
            raw = model.execute(inputs, request.parameters)
            c1 = prof.cpu_now()
            host = _to_host(raw)
            c2 = prof.cpu_now()  # before accounting, like the batch paths
            prof.account("compute", c1 - c0)
            prof.account("readback", c2 - c1)
            return host

    def _package_profiled(
        self, model: Model, request: CoreRequest, raw: Dict[str, np.ndarray]
    ) -> CoreResponse:
        """_package_outputs with its thread-CPU booked under "package" —
        deliberately distinct from the front-ends' "encode" (wire
        serialization): packaging is paid by the in-process path too, so
        folding them together would overstate the wire-only CPU."""
        prof = self.profiling
        if not prof.take():
            return self._package_outputs(model, request, raw)
        c0 = prof.cpu_now()
        try:
            return self._package_outputs(model, request, raw)
        finally:
            prof.account("package", prof.cpu_now() - c0)

    def _package_outputs(
        self, model: Model, request: CoreRequest, raw: Dict[str, np.ndarray]
    ) -> CoreResponse:
        requested = request.outputs
        if not requested:
            # Hot path: the default "all declared outputs" list is
            # per-model-constant; cache it on the model object.
            requested = getattr(model, "_ctpu_default_outputs", None)
            if requested is None:
                requested = [
                    CoreRequestedOutput(name=o["name"])
                    for o in model.outputs
                ]
                model._ctpu_default_outputs = requested
        out_tensors: List[CoreTensor] = []
        shm_outputs: Dict[str, Any] = {}
        for req_out in requested:
            if req_out.name not in raw:
                raise InferenceServerException(
                    f"unexpected inference output '{req_out.name}' for model "
                    f"'{model.name}'"
                )
            arr = raw[req_out.name]
            if type(arr) is not np.ndarray:
                arr = np.asarray(arr)
            if req_out.classification > 0:
                arr = self._classify(model, req_out, arr)
            datatype = np_to_triton_dtype(arr.dtype)
            tensor = CoreTensor(
                name=req_out.name,
                datatype=datatype,
                shape=list(arr.shape),
                data=arr,
            )
            if req_out.shm_region is not None:
                if datatype == "BYTES":
                    payload = serialize_byte_tensor(arr).tobytes()
                else:
                    payload = np.ascontiguousarray(arr).tobytes()
                if len(payload) > req_out.shm_byte_size:
                    raise InferenceServerException(
                        f"shared memory region for output '{req_out.name}' is "
                        f"too small: need {len(payload)} bytes, have "
                        f"{req_out.shm_byte_size}"
                    )
                self.shm.write(req_out.shm_region, req_out.shm_offset, payload)
                shm_outputs[req_out.name] = (
                    req_out.shm_region,
                    len(payload),
                    req_out.shm_offset,
                )
            out_tensors.append(tensor)
        return CoreResponse(
            model_name=model.name,
            model_version=model.version,
            id=request.id,
            outputs=out_tensors,
            shm_outputs=shm_outputs,
        )

    def _classify(
        self, model: Model, req_out: CoreRequestedOutput, arr: np.ndarray
    ) -> np.ndarray:
        """Convert a score tensor to Triton classification strings
        ``"value:index[:label]"`` over the last axis."""
        k = min(req_out.classification, arr.shape[-1])
        labels = model.labels(req_out.name)
        flat = arr.reshape(-1, arr.shape[-1])
        rows = []
        for row in flat:
            top = np.argsort(row)[::-1][:k]
            entries = []
            for idx in top:
                s = f"{row[idx]:f}:{idx}"
                if labels and idx < len(labels):
                    s += f":{labels[idx]}"
                entries.append(s.encode("utf-8"))
            rows.append(entries)
        out = np.array(rows, dtype=np.object_)
        return out.reshape(list(arr.shape[:-1]) + [k])

    def infer_nowait(self, request: CoreRequest) -> "asyncio.Future":
        """Submit a request->response inference; returns its future.

        The allocation-free twin of :meth:`infer` for callback-style
        front-ends (the native gRPC bridge): batchable requests go straight
        to the batcher's future — no coroutine, no task. Other requests
        fall back to a task wrapping the slow path. Raises synchronously on
        validation errors.
        """
        self._lifecycle_admit(request.model_name, request.trace)
        try:
            model = self.repository.get(
                request.model_name, request.model_version
            )
            if model.decoupled:
                raise InferenceServerException(
                    f"model '{model.name}' is decoupled; use streaming "
                    "inference"
                )
            if model.max_batch_size > 1 and self._has_batch_dim(model, request):
                future = self._submit_batched(model, request)
            else:
                ticket = self._admit_single(model, request)
                future = asyncio.ensure_future(
                    self._infer_single(model, request, ticket)
                )
        except BaseException:
            self.lifecycle.finish(request.model_name)
            raise
        self.metrics.pending_inc(model.name)

        def _settled(_f, name=model.name, census=request.model_name):
            self.metrics.pending_dec(name)
            self.lifecycle.finish(census)

        future.add_done_callback(_settled)
        return future

    def _submit_batched(
        self, model: Model, request: CoreRequest
    ) -> "asyncio.Future[CoreResponse]":
        """Route a batchable request to its model's dynamic batcher."""
        batcher = self._batchers.get(model.name)
        if batcher is None or batcher.model is not model:
            batcher = _ModelBatcher(self, model)
            self._batchers[model.name] = batcher
        try:
            return batcher.submit(request)
        except SchedulingError:
            # Admission rejections are fully booked inside submit()
            # (reject counter + stats fail + trace event).
            raise
        except InferenceServerException:
            # Validation failures surface synchronously; execution
            # failures are accounted inside the batcher already.
            self._stats_for(model.name).record("fail", 0)
            raise

    def infer_direct(self, requests: List[CoreRequest]) -> List[Any]:
        """Synchronously execute a batch of unary requests on the CALLING
        thread — no event loop, no futures, no executor hop.

        This is the native gRPC front-end's hot path: its pump thread
        drains parsed requests from C++ and runs them here, so the
        per-request asyncio machinery (future + task + done-callback +
        thread-pool hop) disappears entirely. Dynamic batching still
        applies — compatible requests in ``requests`` merge into one
        device execution exactly as the event-loop batcher would merge
        them, and the C++ ready-queue that accumulates while a batch
        executes is the batching window.

        Returns a list aligned with ``requests``: CoreResponse on
        success, Exception on failure (never raises per-request errors).
        """
        results: List[Any] = [None] * len(requests)
        arrival_ns = time.monotonic_ns()
        # key -> (model, meta, [(index, rows), ...]); ordered by first
        # arrival so same-signature requests execute in request order.
        groups: Dict[Any, Any] = {}
        # repository.get takes the repo lock; under load nearly every
        # request in a batch targets the same model, so resolve once.
        model_cache: Dict[Any, Model] = {}
        # every request admitted into the lifecycle census; this whole
        # call is synchronous, so they all finish before it returns
        admitted: List[str] = []
        for idx, request in enumerate(requests):
            model = None
            grouped = False
            try:
                self._lifecycle_admit(request.model_name, request.trace)
                admitted.append(request.model_name)
                model_key = (request.model_name, request.model_version)
                model = model_cache.get(model_key)
                if model is None:
                    model = self.repository.get(
                        request.model_name, request.model_version
                    )
                    model_cache[model_key] = model
                self.metrics.pending_inc(model.name)
                if model.decoupled:
                    raise InferenceServerException(
                        f"model '{model.name}' is decoupled; use streaming "
                        "inference"
                    )
                if model.max_batch_size > 1 and self._has_batch_dim(
                    model, request
                ):
                    meta = self._batch_meta(model)
                    rows = meta.validate(request)
                    ticket = self._admit_single(model, request)
                    key = (model.name, meta.signature(request))
                    group = groups.get(key)
                    if group is None:
                        groups[key] = (model, meta, [(idx, rows, ticket)])
                    else:
                        group[2].append((idx, rows, ticket))
                    # grouped requests stay pending until their chunk
                    # executes (_execute_direct_chunk decrements)
                    grouped = True
                else:
                    ticket = self._admit_single(model, request)
                    results[idx] = self._infer_single_sync(
                        model, request, ticket
                    )
            except Exception as e:  # noqa: BLE001 - aligned error result
                # Only account stats for models that exist: booking by a
                # client-supplied unknown name would grow self.stats
                # without bound under hostile clients. Admission
                # rejections were fully booked at the rejection site.
                if model is not None and not isinstance(e, SchedulingError):
                    now = time.monotonic_ns()
                    self._stats_for(model.name).record(
                        "fail", now - arrival_ns
                    )
                    self._log_request_error(
                        "request_failed", model.name, e, path="direct"
                    )
                    self._record_exemplar(
                        model.name,
                        request,
                        path="direct",
                        status="error",
                        error=str(e),
                        arrival_ns=arrival_ns,
                        end_ns=now,
                    )
                results[idx] = e
            finally:
                if model is not None and not grouped:
                    self.metrics.pending_dec(model.name)
        try:
            for model, meta, entries in groups.values():
                budget = model.max_batch_size
                chunk: List[Any] = []
                chunk_rows = 0
                for entry in entries:
                    if chunk and chunk_rows + entry[1] > budget:
                        self._execute_direct_chunk(
                            model, meta, chunk, requests, results, arrival_ns
                        )
                        chunk, chunk_rows = [], 0
                    chunk.append(entry)
                    chunk_rows += entry[1]
                if chunk:
                    self._execute_direct_chunk(
                        model, meta, chunk, requests, results, arrival_ns
                    )
        finally:
            for name in admitted:
                self.lifecycle.finish(name)
        return results

    def _execute_direct_chunk(
        self,
        model: Model,
        meta: _BatchMeta,
        chunk: List[Any],
        requests: List[CoreRequest],
        results: List[Any],
        arrival_ns: int,
    ) -> None:
        """One merged device execution for the direct path (the synchronous
        twin of _ModelBatcher._execute_batch). Chunk entries are
        ``(index, rows, admission_ticket)``; entries whose queue deadline
        passed while the chunk formed fail with a deadline error before
        the merge."""
        stats = self._stats_for(model.name)
        policy = self._queue_policy(model)
        check_ns = time.monotonic_ns()
        live = []
        for idx, rows, ticket in chunk:
            if ticket is not None:
                ticket.started()
            request = requests[idx]
            if (
                request.deadline_ns is not None
                and check_ns > request.deadline_ns
                and policy.timeout_action == TIMEOUT_ACTION_REJECT
            ):
                error = QueueTimeoutError(
                    model.name, policy.timeout_us_of(request.parameters)
                )
                self._book_rejection(
                    model.name,
                    request,
                    error,
                    record_fail=True,
                    latency_ns=check_ns - arrival_ns,
                )
                results[idx] = error
                self.metrics.pending_dec(model.name)
            else:
                live.append((idx, rows))
        chunk = live
        if not chunk:
            return
        resources = policy.rate_resources
        if resources:
            self.rate_limiter.acquire_blocking(
                resources, policy.rate_priority
            )
        exec_start = time.monotonic_ns()
        reqs = [requests[idx] for idx, _rows in chunk]
        prof = self.profiling
        n = len(chunk)
        try:
            try:
                if prof.take():
                    prof.account(
                        "queue_wait",
                        0,
                        wall_ns=(exec_start - arrival_ns) * n,
                        count=n,
                    )
                    a0 = prof.cpu_now()
                    merged = meta.merge_inputs(reqs)
                    a1 = prof.cpu_now()
                    with model.placement():
                        raw = model.execute(merged, reqs[0].parameters)
                        a2 = prof.cpu_now()
                        raw = _to_host(raw)
                    a3 = prof.cpu_now()
                    prof.account("batch_assembly", a1 - a0, count=n)
                    prof.account("compute", a2 - a1, count=n)
                    prof.account("readback", a3 - a2, count=n)
                else:
                    merged = meta.merge_inputs(reqs)
                    with model.placement():
                        raw = _to_host(
                            model.execute(merged, reqs[0].parameters)
                        )
            finally:
                if resources:
                    self.rate_limiter.release(resources)
            infer_end = time.monotonic_ns()
            self.add_busy_ns(model, infer_end - exec_start)
            self.metrics.observe_execution(
                model.name, sum(rows for _idx, rows in chunk)
            )
        except Exception as e:  # noqa: BLE001 - fail every request in chunk
            self._log_request_error(
                "batch_execution_failed", model.name, e, path="direct"
            )
            now = time.monotonic_ns()
            for idx, _rows in chunk:
                stats.record("fail", now - arrival_ns)
                self._record_exemplar(
                    model.name,
                    requests[idx],
                    path="direct",
                    status="error",
                    error=str(e),
                    arrival_ns=arrival_ns,
                    exec_start_ns=exec_start,
                    end_ns=now,
                )
                results[idx] = e
            self.metrics.pending_dec(model.name, len(chunk))
            return
        offset = 0
        ok_requests = 0
        ok_rows = 0
        for (idx, rows), request in zip(chunk, reqs):
            try:
                if len(chunk) == 1:
                    sliced = raw
                else:
                    sliced = {
                        k: v[offset : offset + rows] for k, v in raw.items()
                    }
                results[idx] = self._package_profiled(model, request, sliced)
                request_end = time.monotonic_ns()
                _trace_stages(
                    request.trace,
                    arrival_ns,
                    exec_start,
                    infer_end,
                    request_end,
                )
                self._record_exemplar(
                    model.name,
                    request,
                    path="direct",
                    arrival_ns=arrival_ns,
                    exec_start_ns=exec_start,
                    infer_end_ns=infer_end,
                    end_ns=request_end,
                    rows=rows,
                )
                ok_requests += 1
                ok_rows += rows
            except Exception as e:  # noqa: BLE001 - per-request packaging
                self._log_request_error(
                    "packaging_failed", model.name, e, path="direct"
                )
                now = time.monotonic_ns()
                stats.record("fail", now - arrival_ns)
                self._record_exemplar(
                    model.name,
                    request,
                    path="direct",
                    status="error",
                    error=str(e),
                    arrival_ns=arrival_ns,
                    exec_start_ns=exec_start,
                    infer_end_ns=infer_end,
                    end_ns=now,
                    rows=rows,
                )
                results[idx] = e
            offset += rows
        out_end = time.monotonic_ns()
        self.metrics.pending_dec(model.name, len(chunk))
        if ok_requests:
            # One lock + one booking for the whole chunk; packaging time
            # is split evenly across its requests. The ONE device
            # execution is credited once (Triton execution_count
            # semantics).
            stats.record_success_batch(
                ok_requests,
                ok_rows,
                queue_ns_total=(exec_start - arrival_ns) * ok_requests,
                infer_ns_total=(infer_end - exec_start) * ok_requests,
                out_ns_total=out_end - infer_end,
                executions=1,
            )
        else:
            stats.record_execution()

    def _infer_single_sync(
        self, model: Model, request: CoreRequest, ticket=None
    ) -> CoreResponse:
        """Unbatched synchronous execution (the direct-path twin of
        _infer_single); raises on failure, caller accounts the 'fail'
        (admission rejections book themselves). Runs on the native
        front-end's pump thread — its own thread, not the shared
        executor — so a blocking limiter wait here cannot starve the
        execution that would release the grant."""
        stats = self._stats_for(model.name)
        policy = self._queue_policy(model)
        if policy.rate_resources:
            # before t0: the grant wait must not book as device-busy time
            self.rate_limiter.acquire_blocking(
                policy.rate_resources, policy.rate_priority
            )
            try:
                t0 = time.monotonic_ns()
                raw = self._run_single(model, request, ticket)
            finally:
                self.rate_limiter.release(policy.rate_resources)
        else:
            t0 = time.monotonic_ns()
            raw = self._run_single(model, request, ticket)
        t1 = time.monotonic_ns()
        self.add_busy_ns(model, t1 - t0)
        response = self._package_profiled(model, request, raw)
        t2 = time.monotonic_ns()
        rows = self._resolve_batch(model, request)
        self.metrics.observe_execution(model.name, rows)
        stats.record_success(
            rows,
            queue_ns=0,
            in_ns=0,
            infer_ns=t1 - t0,
            out_ns=t2 - t1,
            trace_id=_trace_id_of(request),
        )
        _trace_stages(request.trace, t0, t0, t1, t2)
        self._record_exemplar(
            model.name,
            request,
            path="single",
            arrival_ns=t0,
            exec_start_ns=t0,
            infer_end_ns=t1,
            end_ns=t2,
            rows=rows,
        )
        return response

    async def infer(self, request: CoreRequest) -> CoreResponse:
        """Execute a request->response inference (decoupled models rejected)."""
        self._lifecycle_admit(request.model_name, request.trace)
        try:
            model = self.repository.get(
                request.model_name, request.model_version
            )
            if model.decoupled:
                raise InferenceServerException(
                    f"model '{model.name}' is decoupled; use streaming "
                    "inference"
                )
            self.metrics.pending_inc(model.name)
            try:
                if model.max_batch_size > 1 and self._has_batch_dim(
                    model, request
                ):
                    return await self._submit_batched(model, request)
                # Awaited single path: run the coroutine inline — no Task.
                ticket = self._admit_single(model, request)
                return await self._infer_single(model, request, ticket)
            finally:
                self.metrics.pending_dec(model.name)
        finally:
            # the census covers queued batcher time too: the future above
            # resolves only when the request left the queue and executed
            self.lifecycle.finish(request.model_name)

    async def _infer_single(
        self, model: Model, request: CoreRequest, ticket=None
    ) -> CoreResponse:
        """Unbatched execution path (max_batch_size <= 1 or no batch dim).

        ``ticket`` is the admission-gate slot claimed by the caller; the
        executor closure releases it when execution begins (and the
        finally below is the safety net for requests cancelled before
        their executor slot ran)."""
        stats = self._stats_for(model.name)
        policy = self._queue_policy(model)
        t0 = time.monotonic_ns()
        loop = asyncio.get_running_loop()
        rate_resources = None
        try:
            if policy.rate_resources:
                # waited on the LOOP, never on an executor thread (a
                # parked worker could starve the releasing execution)
                await self.rate_limiter.acquire(
                    policy.rate_resources, policy.rate_priority
                )
                rate_resources = policy.rate_resources
            t1 = time.monotonic_ns()
            raw = await loop.run_in_executor(
                self._executor, self._run_single, model, request, ticket
            )
            t2 = time.monotonic_ns()
            response = self._package_profiled(model, request, raw)
            t3 = time.monotonic_ns()
        except Exception as e:
            # admission rejections (queue timeout) were booked already
            if not isinstance(e, SchedulingError):
                now = time.monotonic_ns()
                stats.record("fail", now - t0)
                self._log_request_error(
                    "request_failed", model.name, e, path="single"
                )
                self._record_exemplar(
                    model.name,
                    request,
                    path="single",
                    status="error",
                    error=str(e),
                    arrival_ns=t0,
                    end_ns=now,
                )
            raise
        finally:
            if rate_resources is not None:
                self.rate_limiter.release(rate_resources)
            if ticket is not None:
                ticket.close()
        self.add_busy_ns(model, t2 - t1)
        rows = self._resolve_batch(model, request)
        self.metrics.observe_execution(model.name, rows)
        stats.record_success(
            rows,
            queue_ns=t1 - t0,
            in_ns=0,
            infer_ns=t2 - t1,
            out_ns=t3 - t2,
            trace_id=_trace_id_of(request),
        )
        if self.profiling.take():
            self.profiling.account("queue_wait", 0, wall_ns=t1 - t0)
        _trace_stages(request.trace, t0, t1, t2, t3)
        self._record_exemplar(
            model.name,
            request,
            path="single",
            arrival_ns=t0,
            exec_start_ns=t1,
            infer_end_ns=t2,
            end_ns=t3,
            rows=rows,
        )
        return response

    async def infer_decoupled(
        self, request: CoreRequest
    ) -> AsyncIterator[CoreResponse]:
        """Execute a streaming inference; yields 0..N responses.

        Non-decoupled models yield exactly one response, so the streaming
        front-end can serve both kinds (Triton semantics).
        """
        model = self.repository.get(request.model_name, request.model_version)
        # Engine-backed models (client_tpu.llm) hook into the server they
        # serve under — metrics registry, executor, structured logger —
        # on first use; one getattr per stream start, idempotent per core.
        bind = getattr(model, "bind_core", None)
        if bind is not None:
            bind(self)
        stats = self._stats_for(model.name)
        ticket = None
        rate_resources = None
        if model.decoupled:
            # Drain gate + census first (non-decoupled delegates to
            # infer(), which runs its own), then admission: the
            # waiting-room bound sheds streams that would only pile up
            # behind a saturated device (raises a booked QueueFullError).
            self._lifecycle_admit(request.model_name, request.trace)
            try:
                ticket = self._admit_single(model, request)
            except BaseException:
                self.lifecycle.finish(request.model_name)
                raise
        t0 = time.monotonic_ns()
        # Split the stream's lifetime into model-compute vs output-packaging
        # time, and record time-to-first-response — the reference's stats
        # treat a stream as one opaque request (its own known blind spot,
        # grpc_client.cc:1650-1653); don't inherit that.
        packaging_ns = 0
        # Device-busy attribution for the stream: only time spent awaiting
        # the model's next item counts (model_wait_ns). The stream's wall
        # time also contains suspension at `yield` while the front-end
        # writes to the consumer — booking that would read a slow client
        # as a busy TPU (duty cycle ~1.0 on an idle device).
        model_wait_ns = 0
        prev_ns = t0
        index = 0
        final_delivered = False

        def _book_success() -> None:
            t1 = time.monotonic_ns()
            self.add_busy_ns(model, model_wait_ns)
            stats.record_success(
                self._resolve_batch(model, request),
                queue_ns=0,
                in_ns=0,
                infer_ns=(t1 - t0) - packaging_ns,
                out_ns=packaging_ns,
                trace_id=_trace_id_of(request),
            )
            _trace_stages(request.trace, t0, t0, t1, t1)
            self._record_exemplar(
                model.name,
                request,
                path="decoupled",
                arrival_ns=t0,
                exec_start_ns=t0,
                infer_end_ns=t1 - packaging_ns,
                end_ns=t1,
                responses=index,
            )

        if model.decoupled:
            # non-decoupled requests delegate to infer(), which tracks its
            # own pending gauge — tracking both would double-count
            self.metrics.pending_inc(model.name)
        try:
            if not model.decoupled:
                yield await self.infer(request)
                return
            policy = self._queue_policy(model)
            if policy.rate_resources:
                # the stream holds its resource grant for its lifetime
                await self.rate_limiter.acquire(
                    policy.rate_resources, policy.rate_priority
                )
                rate_resources = policy.rate_resources
            # Leave the waiting room and re-check the queue deadline only
            # AFTER the grant wait (mirroring _run_single's ordering):
            # streams parked on the pool must keep counting against
            # max_queue_size, and a deadline that passes during the wait
            # must still fail the stream before it touches the model.
            if ticket is not None:
                ticket.started()
            self._check_deadline(model, request)
            inputs = {t.name: t.data for t in request.inputs}
            prof = self.profiling
            resume_ns = time.monotonic_ns()
            # Decoupled models run as async generators on the loop
            # thread; the loop thread's CPU between resuming the model
            # and its next item is the step's compute (an approximation:
            # other tasks interleaved on the loop contaminate it).
            measure_step = prof.take()
            cpu_resume = prof.cpu_now() if measure_step else 0
            async for raw in model.execute_decoupled(inputs, request.parameters):
                final = raw.pop("__final__", False) if isinstance(raw, dict) else False
                p0 = time.monotonic_ns()
                model_wait_ns += p0 - resume_ns
                if measure_step:
                    prof.account("compute", prof.cpu_now() - cpu_resume)
                if raw:
                    response = self._package_profiled(model, request, raw)
                else:
                    response = CoreResponse(
                        model_name=model.name,
                        model_version=model.version,
                        id=request.id,
                        outputs=[],
                    )
                if final:
                    response.parameters["triton_final_response"] = True
                p1 = time.monotonic_ns()
                packaging_ns += p1 - p0
                stats.record_response(
                    index,
                    infer_ns=p0 - prev_ns,
                    out_ns=p1 - p0,
                    latency_ns=p1 - t0,
                    empty=not raw,
                )
                if request.trace is not None:
                    request.trace.event(f"RESPONSE_{index}", p1)
                prev_ns = p1
                index += 1
                # A close/cancel that arrives while suspended at this yield
                # means the yielded value WAS delivered — so a final-marked
                # response makes the stream complete, not cancelled (clients
                # routinely stop iterating at triton_final_response).
                final_delivered = final
                yield response
                # back from the consumer; the next await is model time
                resume_ns = time.monotonic_ns()
                measure_step = prof.take()
                if measure_step:
                    cpu_resume = prof.cpu_now()
        except (asyncio.CancelledError, GeneratorExit):
            # Task cancellation (gRPC stream teardown) and generator close
            # (HTTP/OpenAI front-end client disconnect): if the final
            # response was already delivered this is normal completion;
            # otherwise book a cancel entry at the in-flight response index.
            if model.decoupled:
                if final_delivered:
                    _book_success()
                else:
                    stats.record_response_failure(
                        index, time.monotonic_ns() - t0, cancelled=True
                    )
            raise
        except Exception as e:
            # Only the decoupled path accounts here: non-decoupled requests
            # were delegated to infer(), which already recorded the failure
            # (recording again would double-count it).
            if model.decoupled:
                now = time.monotonic_ns()
                # Book the in-flight response slot too, not just the
                # aggregate: response_stats mirrors Triton's
                # InferResponseStatistics, which carries fail entries.
                stats.record_response_failure(index, now - t0)
                # admission rejections booked their aggregate fail already
                if not isinstance(e, SchedulingError):
                    stats.record("fail", now - t0)
                    self._log_request_error(
                        "stream_failed", model.name, e, path="decoupled"
                    )
                    self._record_exemplar(
                        model.name,
                        request,
                        path="decoupled",
                        status="error",
                        error=str(e),
                        arrival_ns=t0,
                        end_ns=now,
                        responses=index,
                    )
            raise
        else:
            _book_success()
        finally:
            if rate_resources is not None:
                self.rate_limiter.release(rate_resources)
            if ticket is not None:
                ticket.close()
            if model.decoupled:
                self.metrics.pending_dec(model.name)
                self.lifecycle.finish(request.model_name)

    # -- wire-side input decoding -------------------------------------------

    def decode_input(
        self,
        name: str,
        datatype: str,
        shape: List[int],
        raw: Optional[bytes] = None,
        json_data: Optional[list] = None,
        shm_region: Optional[str] = None,
        shm_byte_size: int = 0,
        shm_offset: int = 0,
    ) -> CoreTensor:
        """Materialize an input tensor from any of the three data sources
        (inline binary, JSON, shared memory)."""
        count = num_elements(shape)
        if shm_region is not None:
            # Zero-copy view into the registered region (np.frombuffer
            # below wraps it without copying). Read-only so a model that
            # mutates its input in place raises instead of silently
            # corrupting the client's region. The region must stay
            # registered while requests that reference it are in flight —
            # same contract as the reference server's direct shm reads.
            raw = self.shm.read(
                shm_region, shm_offset, shm_byte_size
            ).toreadonly()
        if raw is not None:
            if datatype == "BYTES":
                arr = deserialize_bytes_tensor(raw).reshape(shape)
            else:
                np_dtype = triton_to_np_dtype(datatype)
                if np_dtype is None:
                    raise InferenceServerException(
                        f"unsupported datatype '{datatype}' for input '{name}'"
                    )
                expected = count * np_dtype.itemsize
                if len(raw) != expected:
                    raise InferenceServerException(
                        f"input '{name}' expected {expected} bytes for shape "
                        f"{shape} and datatype {datatype}, got {len(raw)}"
                    )
                arr = np.frombuffer(raw, dtype=np_dtype).reshape(shape)
        elif json_data is not None:
            if datatype == "BYTES":
                arr = np.array(
                    [
                        d.encode("utf-8") if isinstance(d, str) else d
                        for d in json_data
                    ],
                    dtype=np.object_,
                ).reshape(shape)
            else:
                np_dtype = triton_to_np_dtype(datatype)
                if np_dtype is None:
                    raise InferenceServerException(
                        f"unsupported datatype '{datatype}' for input '{name}'"
                    )
                arr = np.array(json_data, dtype=np_dtype).reshape(shape)
        else:
            raise InferenceServerException(
                f"input '{name}' has no data (inline, JSON, or shared memory)"
            )
        return CoreTensor(name=name, datatype=datatype, shape=list(shape), data=arr)
