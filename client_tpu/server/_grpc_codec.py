"""Shared gRPC method codec: proto bytes <-> ServerCore calls.

Every non-inference RPC of inference.GRPCInferenceService is a synchronous
request->response exchange over :class:`ServerCore`. This module implements
them once, operating on serialized protobuf messages, so both front-ends —
the grpc.aio servicer (`grpc_server.py`) and the native C++ h2 front-end
(`native_frontend.py`), which hands undecoded method payloads to Python —
share one implementation (reference: the per-method handlers in
src/grpc/grpc_server.cc are likewise shared across that server's endpoints).
"""

from typing import Any, Callable, Dict, Tuple

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._generated import model_config_pb2 as mc
from client_tpu.server.core import (
    SERVER_EXTENSIONS,
    SERVER_NAME,
    SERVER_VERSION,
    ServerCore,
)
from client_tpu.utils import InferenceServerException

# gRPC status codes (subset used here; numeric so the native front-end can
# put them straight into the grpc-status trailer).
GRPC_OK = 0
GRPC_DEADLINE_EXCEEDED = 4
GRPC_INVALID_ARGUMENT = 3
GRPC_NOT_FOUND = 5
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_UNIMPLEMENTED = 12
GRPC_INTERNAL = 13
GRPC_UNAVAILABLE = 14

# grpc.StatusCode names (as carried by SchedulingError.grpc_code) ->
# numeric codes, for exception-aware callers.
_CODE_BY_NAME = {
    "DEADLINE_EXCEEDED": GRPC_DEADLINE_EXCEEDED,
    "INVALID_ARGUMENT": GRPC_INVALID_ARGUMENT,
    "NOT_FOUND": GRPC_NOT_FOUND,
    "RESOURCE_EXHAUSTED": GRPC_RESOURCE_EXHAUSTED,
    "UNIMPLEMENTED": GRPC_UNIMPLEMENTED,
    "INTERNAL": GRPC_INTERNAL,
    "UNAVAILABLE": GRPC_UNAVAILABLE,
}


def status_code_for(message: str, exc=None) -> int:
    """Map an InferenceServerException (or its message) to a gRPC status
    code. Exceptions that declare ``grpc_code`` (the scheduling layer's
    admission rejections) win; message patterns cover callers that only
    have the text (the native front-end's completion path)."""
    if exc is not None:
        code = _CODE_BY_NAME.get(getattr(exc, "grpc_code", None))
        if code is not None:
            return code
    lowered = message.lower()
    if "queue" in lowered and "full" in lowered:
        return GRPC_RESOURCE_EXHAUSTED
    if "timed out in queue" in lowered:
        return GRPC_DEADLINE_EXCEEDED
    if "not found" in lowered or "unknown model" in lowered:
        return GRPC_NOT_FOUND
    if (
        "not ready" in lowered
        or "unavailable" in lowered
        or "draining" in lowered
        or "not accepting new inference" in lowered
    ):
        return GRPC_UNAVAILABLE
    if "not implemented" in lowered or "no cuda" in lowered:
        return GRPC_UNIMPLEMENTED
    return GRPC_INVALID_ARGUMENT


class RpcError(Exception):
    """A method failure carrying its gRPC status code."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def params_to_dict(proto_params) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, p in proto_params.items():
        which = p.WhichOneof("parameter_choice")
        if which is not None:
            out[key] = getattr(p, which)
    return out


def dict_to_params(values: Dict[str, Any], proto_params) -> None:
    for key, value in values.items():
        if isinstance(value, bool):
            proto_params[key].bool_param = value
        elif isinstance(value, int):
            proto_params[key].int64_param = value
        elif isinstance(value, float):
            proto_params[key].double_param = value
        else:
            proto_params[key].string_param = str(value)


# -- per-method handlers (request proto -> response proto) -------------------


def _server_live(core: ServerCore, request):
    return pb.ServerLiveResponse(live=core.live)


def _server_ready(core: ServerCore, request):
    # Real readiness (was a copy of _server_live): live AND accepting
    # (drain-aware) AND repository ready set non-degraded. Shared by the
    # grpc.aio servicer and the native C++ front-end.
    return pb.ServerReadyResponse(ready=core.ready)


def _model_ready(core: ServerCore, request):
    return pb.ModelReadyResponse(
        ready=core.repository.is_ready(request.name, request.version)
    )


def _server_metadata(core: ServerCore, request):
    return pb.ServerMetadataResponse(
        name=SERVER_NAME, version=SERVER_VERSION, extensions=SERVER_EXTENSIONS
    )


def _model_metadata(core: ServerCore, request):
    model = core.repository.get(request.name, request.version)
    meta = model.metadata()
    response = pb.ModelMetadataResponse(
        name=meta["name"],
        versions=meta["versions"],
        platform=meta["platform"],
    )
    for io_key, target in (
        ("inputs", response.inputs),
        ("outputs", response.outputs),
    ):
        for tensor in meta[io_key]:
            target.add(
                name=tensor["name"],
                datatype=tensor["datatype"],
                shape=tensor["shape"],
            )
    return response


def _model_config(core: ServerCore, request):
    model = core.repository.get(request.name, request.version)
    cfg = model.config()
    proto = mc.ModelConfig(
        name=cfg["name"],
        platform=cfg["platform"],
        backend=cfg["backend"],
        max_batch_size=cfg["max_batch_size"],
    )
    for tensor in cfg["input"]:
        proto.input.add(
            name=tensor["name"],
            data_type=mc.DataType.Value(tensor["data_type"]),
            dims=tensor["dims"],
        )
    for tensor in cfg["output"]:
        proto.output.add(
            name=tensor["name"],
            data_type=mc.DataType.Value(tensor["data_type"]),
            dims=tensor["dims"],
        )
    proto.model_transaction_policy.decoupled = cfg["model_transaction_policy"][
        "decoupled"
    ]
    # Scheduler declarations (reference model_parser.cc detection inputs).
    if "dynamic_batching" in cfg:
        proto.dynamic_batching.SetInParent()
    if "sequence_batching" in cfg:
        proto.sequence_batching.SetInParent()
    # Free-form config parameters (the "mesh" topology document for
    # sharded models — the gRPC face of the HTTP metadata devices block).
    for key, value in cfg.get("parameters", {}).items():
        proto.parameters[key].string_value = value.get("string_value", "")
    if "ensemble_scheduling" in cfg:
        for step in cfg["ensemble_scheduling"].get("step", []):
            entry = proto.ensemble_scheduling.step.add(
                model_name=step["model_name"],
                model_version=int(step.get("model_version", -1)),
            )
            entry.input_map.update(step.get("input_map", {}))
            entry.output_map.update(step.get("output_map", {}))
    return pb.ModelConfigResponse(config=proto)


def _model_statistics(core: ServerCore, request):
    stats = core.statistics(request.name, request.version)
    response = pb.ModelStatisticsResponse()
    for snap in stats["model_stats"]:
        entry = response.model_stats.add(
            name=snap["name"],
            version=snap["version"],
            last_inference=snap["last_inference"],
            inference_count=snap["inference_count"],
            execution_count=snap["execution_count"],
        )
        for field, duration in snap["inference_stats"].items():
            target = getattr(entry.inference_stats, field)
            target.count = duration["count"]
            target.ns = duration["ns"]
        for key, fields in snap.get("response_stats", {}).items():
            rs = entry.response_stats[key]
            for field, duration in fields.items():
                target = getattr(rs, field)
                target.count = duration["count"]
                target.ns = duration["ns"]
    return response


def _repository_index(core: ServerCore, request):
    response = pb.RepositoryIndexResponse()
    for entry in core.repository.index():
        if request.ready and entry["state"] != "READY":
            continue
        response.models.add(**entry)
    return response


def _repository_model_load(core: ServerCore, request):
    params = params_to_dict(request.parameters)
    config = params.get("config")
    core.load_model(
        request.model_name,
        config_override=config if isinstance(config, str) else None,
    )
    return pb.RepositoryModelLoadResponse()


def _repository_model_unload(core: ServerCore, request):
    # Drain-aware unload through the core (see ServerCore.unload_model);
    # the RPC returns once the model stops admitting — the drain itself
    # runs in the background, Triton-style.
    core.unload_model(request.model_name)
    return pb.RepositoryModelUnloadResponse()


def _system_shm_status(core: ServerCore, request):
    response = pb.SystemSharedMemoryStatusResponse()
    for name, region in core.shm.status("system", request.name).items():
        response.regions[name].name = region["name"]
        response.regions[name].key = region["key"]
        response.regions[name].offset = region["offset"]
        response.regions[name].byte_size = region["byte_size"]
    return response


def _system_shm_register(core: ServerCore, request):
    core.shm.register_system(
        request.name, request.key, request.offset, request.byte_size
    )
    return pb.SystemSharedMemoryRegisterResponse()


def _system_shm_unregister(core: ServerCore, request):
    if request.name:
        core.shm.unregister(request.name, kind="system")
    else:
        core.shm.unregister_all(kind="system")
    return pb.SystemSharedMemoryUnregisterResponse()


def _cuda_shm_status(core: ServerCore, request):
    return pb.CudaSharedMemoryStatusResponse()


def _cuda_shm_register(core: ServerCore, request):
    raise RpcError(
        GRPC_UNIMPLEMENTED,
        "this server has no CUDA devices; use TPU or system shared memory",
    )


def _cuda_shm_unregister(core: ServerCore, request):
    return pb.CudaSharedMemoryUnregisterResponse()


def _tpu_shm_status(core: ServerCore, request):
    response = pb.TpuSharedMemoryStatusResponse()
    for name, region in core.shm.status("tpu", request.name).items():
        response.regions[name].name = region["name"]
        response.regions[name].device_id = region["device_id"]
        response.regions[name].byte_size = region["byte_size"]
        response.regions[name].key = region["key"]
    return response


def _tpu_shm_register(core: ServerCore, request):
    core.shm.register_tpu(
        request.name, request.raw_handle, request.device_id, request.byte_size
    )
    return pb.TpuSharedMemoryRegisterResponse()


def _tpu_shm_unregister(core: ServerCore, request):
    if request.name:
        core.shm.unregister(request.name, kind="tpu")
    else:
        core.shm.unregister_all(kind="tpu")
    return pb.TpuSharedMemoryUnregisterResponse()


def _trace_setting(core: ServerCore, request):
    """The trace-settings RPC, backed by the real TraceManager: validated
    updates (unknown keys / wrong types -> INVALID_ARGUMENT), per-model
    overrides via ``model_name``, and an empty value clearing a setting
    (Triton semantics)."""
    updates = {}
    for key, value in request.settings.items():
        updates[key] = list(value.value) if value.value else None
    if updates:
        settings = core.trace_manager.update(updates, request.model_name)
    else:
        settings = core.trace_manager.settings(request.model_name)
    response = pb.TraceSettingResponse()
    for key, value in settings.items():
        values = value if isinstance(value, list) else [str(value)]
        response.settings[key].value.extend([str(v) for v in values])
    return response


def _log_settings(core: ServerCore, request):
    """The logging-settings RPC, backed by the real structured logger:
    updates change what the server emits immediately. The proto carries
    no model field, so a per-model override rides in as a reserved
    "model" settings key (the HTTP face accepts the same key alongside
    its /v2/models/{model}/logging route)."""
    updates = {}
    for key, value in request.settings.items():
        which = value.WhichOneof("parameter_choice")
        if which is not None:
            updates[key] = getattr(value, which)
    model = updates.pop("model", "")
    if not isinstance(model, str):
        raise InferenceServerException(
            f"log setting 'model' expects a string, got {model!r}"
        )
    settings = core.update_log_settings(updates, model)
    response = pb.LogSettingsResponse()
    for key, value in settings.items():
        if isinstance(value, bool):
            response.settings[key].bool_param = value
        elif isinstance(value, int):
            response.settings[key].uint32_param = value
        else:
            response.settings[key].string_param = str(value)
    return response


# method name (last :path segment) -> (request class, handler)
METHODS: Dict[str, Tuple[Any, Callable]] = {
    "ServerLive": (pb.ServerLiveRequest, _server_live),
    "ServerReady": (pb.ServerReadyRequest, _server_ready),
    "ModelReady": (pb.ModelReadyRequest, _model_ready),
    "ServerMetadata": (pb.ServerMetadataRequest, _server_metadata),
    "ModelMetadata": (pb.ModelMetadataRequest, _model_metadata),
    "ModelConfig": (pb.ModelConfigRequest, _model_config),
    "ModelStatistics": (pb.ModelStatisticsRequest, _model_statistics),
    "RepositoryIndex": (pb.RepositoryIndexRequest, _repository_index),
    "RepositoryModelLoad": (pb.RepositoryModelLoadRequest, _repository_model_load),
    "RepositoryModelUnload": (
        pb.RepositoryModelUnloadRequest,
        _repository_model_unload,
    ),
    "SystemSharedMemoryStatus": (
        pb.SystemSharedMemoryStatusRequest,
        _system_shm_status,
    ),
    "SystemSharedMemoryRegister": (
        pb.SystemSharedMemoryRegisterRequest,
        _system_shm_register,
    ),
    "SystemSharedMemoryUnregister": (
        pb.SystemSharedMemoryUnregisterRequest,
        _system_shm_unregister,
    ),
    "CudaSharedMemoryStatus": (
        pb.CudaSharedMemoryStatusRequest,
        _cuda_shm_status,
    ),
    "CudaSharedMemoryRegister": (
        pb.CudaSharedMemoryRegisterRequest,
        _cuda_shm_register,
    ),
    "CudaSharedMemoryUnregister": (
        pb.CudaSharedMemoryUnregisterRequest,
        _cuda_shm_unregister,
    ),
    "TpuSharedMemoryStatus": (pb.TpuSharedMemoryStatusRequest, _tpu_shm_status),
    "TpuSharedMemoryRegister": (
        pb.TpuSharedMemoryRegisterRequest,
        _tpu_shm_register,
    ),
    "TpuSharedMemoryUnregister": (
        pb.TpuSharedMemoryUnregisterRequest,
        _tpu_shm_unregister,
    ),
    "TraceSetting": (pb.TraceSettingRequest, _trace_setting),
    "LogSettings": (pb.LogSettingsRequest, _log_settings),
}


def handle_method(core: ServerCore, method: str, request_proto):
    """Run one non-inference method on a decoded request proto.

    Returns the response proto; raises :class:`RpcError` on failure.
    Thread-CPU books under the "rpc" profiling stage when stage-CPU
    accounting is enabled: statistics/metadata scrapes share the serving
    threads, so their cycles are part of the wire path's CPU bill and
    must show up in the attribution, not hide in the unaccounted rest.
    Both gRPC faces route here (grpc.aio directly, the native C++
    front-end via :func:`handle_method_bytes`), so one bracket covers
    both.
    """
    entry = METHODS.get(method)
    if entry is None:
        raise RpcError(GRPC_UNIMPLEMENTED, f"unknown method '{method}'")
    from client_tpu.observability.profiling import stage_scope

    with stage_scope(core.profiling, "rpc"):
        try:
            return entry[1](core, request_proto)
        except RpcError:
            raise
        except InferenceServerException as e:
            raise RpcError(status_code_for(e.message()), e.message()) from e


def handle_method_bytes(core: ServerCore, method: str, payload: bytes) -> bytes:
    """Wire-level entry for the native front-end: parse, run, serialize."""
    entry = METHODS.get(method)
    if entry is None:
        raise RpcError(GRPC_UNIMPLEMENTED, f"unknown method '{method}'")
    request = entry[0]()
    try:
        request.ParseFromString(payload)
    except Exception as e:  # noqa: BLE001 - malformed wire bytes
        raise RpcError(GRPC_INTERNAL, f"failed to parse {method} request: {e}")
    return handle_method(core, method, request).SerializeToString()


# -- protobuf-free ModelInfer fast path ---------------------------------------
#
# The common small-request shape (raw tensor contents, no per-tensor
# parameters, no typed contents) decodes and encodes through the
# hand-rolled wire scanner in client_tpu.grpc._wire — no protobuf objects
# on the hot path. Anything else falls back to the proto codec above, so
# the wire contract never forks; parity is guarded byte-exactly by the
# corpus in tests/test_shm_ring.py.


class ScratchBuffer:
    """A reusable, *bounded* bytearray for wire encoding.

    One oversized response must not pin its peak for the connection's
    lifetime: after an encode that grew the buffer past ``cap_bytes``
    the buffer is released and a fresh default-sized one allocated on
    next use (satellite: bounded per-connection scratch)."""

    DEFAULT_BYTES = 1 << 16

    __slots__ = ("cap_bytes", "_buf", "high_water")

    def __init__(self, cap_bytes: int = 4 << 20):
        self.cap_bytes = cap_bytes
        self._buf = bytearray()
        self.high_water = 0

    def take(self) -> bytearray:
        """The cleared scratch (hold only across one synchronous encode)."""
        buf = self._buf
        del buf[:]
        return buf

    def seal(self, buf: bytearray) -> bytes:
        """Snapshot the encoded bytes and apply the shrink policy."""
        data = bytes(buf)
        if len(buf) > self.high_water:
            self.high_water = len(buf)
        if len(buf) > self.cap_bytes:
            self._buf = bytearray()
        return data

    @property
    def capacity(self) -> int:
        """Currently retained backing capacity (for the bound test)."""
        return len(self._buf) if self._buf is not None else 0


class FastInferCodec:
    """Per-front-end ModelInfer codec: fast path + proto fallback.

    One instance per servicer/pump (its scratch is reused across
    requests and must not be shared across threads). Books
    ``tpu_codec_fastpath_total{outcome}`` per decode and falls back to
    the proto codec for any shape the scanner declines.
    """

    _CACHE_MAX = 512  # bounded like the scanner's prefix cache

    def __init__(self, core: ServerCore, scratch_cap_bytes: int = 4 << 20):
        import numpy as np

        from client_tpu.grpc import _wire
        from client_tpu.server import core as core_mod
        from client_tpu.utils import (
            deserialize_bytes_tensor,
            num_elements,
            serialize_byte_tensor,
            triton_to_np_dtype,
        )

        self._wire = _wire
        self._np = np
        self._CoreRequest = core_mod.CoreRequest
        self._CoreTensor = core_mod.CoreTensor
        self._CoreRequestedOutput = core_mod.CoreRequestedOutput
        self._deserialize_bytes = deserialize_bytes_tensor
        self._serialize_bytes = serialize_byte_tensor
        self._num_elements = num_elements
        self._triton_to_np = triton_to_np_dtype
        self.core = core
        self.scratch = ScratchBuffer(scratch_cap_bytes)
        self._scanner = _wire.RequestScanner()
        self._metrics = core.metrics
        # encode-side templates: serialized fields 1-2 per (model,
        # version) and the concatenated outputs-meta block per tensor
        # signature — responses under load share both
        self._head_cache: Dict[Tuple[str, str], bytes] = {}
        self._meta_cache: Dict[Any, bytes] = {}

    # -- decode --------------------------------------------------------------

    def _prepare(self, template):
        """Per-template decode plan, computed once per cached prefix:
        (name, datatype, shape, np dtype or None-for-BYTES or
        False-for-unknown, expected bytes) per input, plus the shared
        CoreRequestedOutput objects (read-only downstream)."""
        plan = []
        for name, datatype, shape in template.inputs:
            if datatype == "BYTES":
                plan.append((name, datatype, shape, None, -1))
                continue
            np_dtype = self._triton_to_np(datatype)
            if np_dtype is None:
                plan.append((name, datatype, shape, False, 0))
            else:
                expected = self._num_elements(shape) * np_dtype.itemsize
                plan.append((name, datatype, shape, np_dtype, expected))
        outputs = [
            self._CoreRequestedOutput(name=n) for n in template.output_names
        ]
        template.prepared = (plan, outputs)
        return template.prepared

    def decode_request(self, data):
        """Serialized ModelInferRequest bytes -> CoreRequest, or None
        when the request is outside the fast shape (caller parses with
        the proto codec). Raises InferenceServerException on invalid
        tensor framing (same messages as ``ServerCore.decode_input``)."""
        wire = self._wire
        try:
            scanned = self._scanner.scan(data)
        except wire.WireError:
            scanned = None
        if scanned is None:
            self._metrics.observe_codec("fallback")
            return None
        template, request_id, extra_params, raws = scanned
        self._metrics.observe_codec("hit")
        prepared = template.prepared
        if prepared is None:
            prepared = self._prepare(template)
        plan, req_outputs = prepared
        n_raw = len(raws)
        if n_raw != len(plan):
            if n_raw < len(plan):
                raise InferenceServerException(
                    f"input '{plan[n_raw][0]}' has no data (inline, JSON, "
                    "or shared memory)"
                )
            raise InferenceServerException(
                f"raw_input_contents has {n_raw} entries but only "
                f"{len(plan)} non-shared-memory inputs consumed them"
            )
        np = self._np
        CoreTensor = self._CoreTensor
        inputs = []
        for i, (name, datatype, shape, np_dtype, expected) in enumerate(plan):
            raw = raws[i]
            if np_dtype is None:
                arr = self._deserialize_bytes(bytes(raw)).reshape(shape)
            elif np_dtype is False:
                raise InferenceServerException(
                    f"unsupported datatype '{datatype}' for input '{name}'"
                )
            else:
                if len(raw) != expected:
                    raise InferenceServerException(
                        f"input '{name}' expected {expected} bytes for "
                        f"shape {shape} and datatype {datatype}, got "
                        f"{len(raw)}"
                    )
                arr = np.frombuffer(raw, dtype=np_dtype).reshape(shape)
            inputs.append(CoreTensor(name, datatype, shape, arr))
        # the template is shared across requests: copy before the
        # ring/scheduling layers pop entries out of it; excised
        # per-request params (ring slot/seq) merge back in
        if template.parameters:
            parameters = dict(template.parameters)
            if extra_params:
                parameters.update(extra_params)
        else:
            parameters = dict(extra_params) if extra_params else {}
        return self._CoreRequest(
            model_name=template.model_name,
            model_version=template.model_version,
            id=request_id,
            inputs=inputs,
            outputs=list(req_outputs) if req_outputs else [],
            parameters=parameters,
        )

    # -- encode --------------------------------------------------------------

    def _response_parts(self, core_response):
        """CoreResponse -> (outputs meta, raw contents) for the wire
        builder; mirrors build_proto_response field for field."""
        import numpy as np

        from client_tpu.utils import serialize_byte_tensor

        outputs = []
        raws = []
        shm_outputs = core_response.shm_outputs
        for t in core_response.outputs:
            shm = shm_outputs.get(t.name)
            if shm is not None:
                region, size, offset = shm
                params = {
                    "shared_memory_region": region,
                    "shared_memory_byte_size": int(size),
                }
                if offset:
                    params["shared_memory_offset"] = int(offset)
                outputs.append((t.name, t.datatype, t.shape, params))
                raws.append(b"")
            elif t.datatype == "BYTES":
                outputs.append((t.name, t.datatype, t.shape, None))
                raws.append(serialize_byte_tensor(t.data).tobytes())
            else:
                data = t.data
                if type(data) is not np.ndarray or not data.flags.c_contiguous:
                    data = np.ascontiguousarray(data)
                outputs.append((t.name, t.datatype, t.shape, None))
                raws.append(data.data.cast("B") if data.ndim else data.tobytes())
        return outputs, raws

    def encode_response(self, core_response) -> bytes:
        """CoreResponse -> serialized ModelInferResponse bytes (never
        fails over the wire: shapes the hand encoder declines fall back
        to the proto builder)."""
        np = self._np
        serialize_byte_tensor = self._serialize_bytes
        wire = self._wire
        try:
            if core_response.shm_outputs:
                # per-output parameter maps: rare path, build in full
                buf = self.scratch.take()
                outputs, raws = self._response_parts(core_response)
                wire.encode_infer_response(
                    buf,
                    core_response.model_name,
                    core_response.model_version,
                    core_response.id,
                    core_response.parameters,
                    outputs,
                    raws,
                )
                return self.scratch.seal(buf)
            buf = self.scratch.take()
            head_key = (core_response.model_name, core_response.model_version)
            head = self._head_cache.get(head_key)
            if head is None:
                if len(self._head_cache) >= self._CACHE_MAX:
                    self._head_cache.clear()
                head = self._head_cache[head_key] = wire.encode_head(
                    *head_key
                )
            buf += head
            if core_response.id:
                rid = core_response.id.encode("utf-8")
                buf.append(0x1A)
                wire.write_varint(buf, len(rid))
                buf += rid
            if core_response.parameters:
                wire._encode_params_map(buf, 0x22, core_response.parameters)
            tensors = core_response.outputs
            meta_key = tuple(
                (t.name, t.datatype, tuple(t.shape)) for t in tensors
            )
            meta = self._meta_cache.get(meta_key)
            if meta is None:
                if len(self._meta_cache) >= self._CACHE_MAX:
                    self._meta_cache.clear()
                meta = self._meta_cache[meta_key] = (
                    wire.encode_output_meta_block(meta_key)
                )
            buf += meta
            for t in tensors:
                if t.datatype == "BYTES":
                    raw = serialize_byte_tensor(t.data).tobytes()
                else:
                    data = t.data
                    if (
                        type(data) is not np.ndarray
                        or not data.flags.c_contiguous
                    ):
                        data = np.ascontiguousarray(data)
                    raw = (
                        data.data.cast("B") if data.ndim else data.tobytes()
                    )
                buf.append(0x32)
                wire.write_varint(buf, len(raw))
                buf += raw
            return self.scratch.seal(buf)
        except Exception:  # noqa: BLE001 - parity net: proto must agree
            self._metrics.observe_codec("encode_fallback")
            from client_tpu.server.grpc_server import build_proto_response

            return build_proto_response(core_response).SerializeToString()

    def encode_stream_response(self, core_response) -> bytes:
        """CoreResponse -> serialized ModelStreamInferResponse bytes."""
        body = self.encode_response(core_response)
        buf = self.scratch.take()
        self._wire.encode_stream_response(buf, body)
        return self.scratch.seal(buf)

    def encode_stream_error(self, message: str, request_id: str = "") -> bytes:
        """In-band stream error frame (error_message + id-only response)."""
        inner = bytearray()
        self._wire.encode_infer_response(
            inner, "", "", request_id, None, (), ()
        )
        buf = self.scratch.take()
        self._wire.encode_stream_response(
            buf, bytes(inner), error_message=message
        )
        return self.scratch.seal(buf)
