"""Server metrics: the registry behind ``/metrics``.

Triton-parity metric families per model (the TPU face of the reference's
``nv_inference_*``/``nv_gpu_*`` families that perf_analyzer's
MetricsManager scrapes, reference metrics_manager.h:45-92,
metrics.h:37-42), built on the dependency-free registry in
:mod:`client_tpu.observability.metrics`:

===================================  =========  ==============================
family                               type       source
===================================  =========  ==============================
tpu_inference_request_success        counter    ServerCore stage events
tpu_inference_request_failure        counter    ServerCore stage events
tpu_inference_request_duration       histogram  per request, seconds
tpu_inference_queue_duration         histogram  per request, seconds
tpu_inference_compute_duration       histogram  per request, seconds
tpu_inference_batch_size             histogram  per device execution, rows
tpu_pending_request_count            gauge      in-flight requests per model
tpu_request_cpu_seconds              histogram  per request thread-CPU {stage}
tpu_queue_rejected_total             counter    admission rejections {model,reason}
tpu_queue_depth                      gauge      queued requests {model,level}
tpu_frontend_request_errors          counter    requests rejected pre-core
tpu_duty_cycle                       gauge      busy-ns counter, scrape delta
tpu_device_compute_ns_total          counter    ServerCore busy-ns {device}
tpu_device_memory_bytes              gauge      jax memory_stats() {device}
tpu_memory_used_bytes (+limit/util)  gauge      jax device memory_stats()
tpu_inference_count (+duration_ns,   counter    statistics extension mirror
  fail_count)                                   (pre-registry wire names)
===================================  =========  ==============================

The histograms are fed from the same ServerCore stage events the
TraceManager receives, so ``/metrics``, the statistics extension, and the
gRPC ModelStatistics RPC all agree: a histogram's ``_count`` equals the
statistics ``success.count`` and its ``_sum`` equals ``success.ns / 1e9``.

Duty cycle is derived from ServerCore's monotone cumulative busy-ns
counter (device executions only — host-placed models never report the
TPU busy): each scrape books busy-delta / wall-delta since the previous
scrape under a lock, so concurrent scrapers each see a consistent (if
shorter) interval and the first scrape reports utilization since server
start instead of a hard-coded 0. Scrapers that want full control (the
perf collector) derive their own rate from ``tpu_device_compute_ns_total``.
"""

import threading
import time
from typing import Callable, Optional

from client_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from client_tpu.observability.slo import LiveTelemetry, SloObjective

try:  # jax powers the optional device-memory gauges
    import jax
except Exception:  # pragma: no cover - jax is an optional extra
    jax = None

# Seconds buckets tuned for TPU relays: sub-ms host models through
# multi-second LLM decodes.
DURATION_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
# Tokens per speculative verify step per sequence: 1 (nothing accepted)
# up through deep-lookahead acceptance; draft windows beyond 16 are
# past the point of diminishing returns for any measured workload.
SPEC_TOKENS_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16)
# Thread-CPU per stage per request: sub-microsecond codec touches through
# multi-millisecond model compute.
STAGE_CPU_BUCKETS_S = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5,
    1e-4, 2.5e-4, 5e-4, 1e-3, 1e-2, 1e-1,
)


class ServerMetrics:
    """Owns the server registry and the hot-path observation methods.

    One instance per :class:`~client_tpu.server.core.ServerCore`; the
    core's execution paths call ``observe_*``/``pending_*`` as requests
    move through, and both front-ends render scrapes via :meth:`render`.
    ``clock_ns`` is injectable (fake-clock tests).
    """

    def __init__(
        self,
        core,
        clock_ns: Callable[[], int] = time.monotonic_ns,
        jax_module=jax,
    ):
        self.core = core
        self._clock_ns = clock_ns
        self._jax = jax_module
        registry = self.registry = MetricsRegistry()
        model = ("model",)
        self.request_success = Counter(
            "tpu_inference_request_success",
            "Successful inference requests.",
            model,
            registry=registry,
        )
        self.request_failure = Counter(
            "tpu_inference_request_failure",
            "Failed inference requests.",
            model,
            registry=registry,
        )
        self.request_duration = Histogram(
            "tpu_inference_request_duration",
            "End-to-end request duration inside the server, in seconds "
            "(queue + compute).",
            model,
            buckets=DURATION_BUCKETS_S,
            registry=registry,
        )
        self.queue_duration = Histogram(
            "tpu_inference_queue_duration",
            "Time a request waited for a device execution slot, in seconds.",
            model,
            buckets=DURATION_BUCKETS_S,
            registry=registry,
        )
        self.compute_duration = Histogram(
            "tpu_inference_compute_duration",
            "Model compute time per request (input + infer + output), in "
            "seconds.",
            model,
            buckets=DURATION_BUCKETS_S,
            registry=registry,
        )
        self.batch_size = Histogram(
            "tpu_inference_batch_size",
            "Rows per device execution (dynamic batcher merge size).",
            model,
            buckets=BATCH_SIZE_BUCKETS,
            registry=registry,
        )
        self.pending_requests = Gauge(
            "tpu_pending_request_count",
            "Inference requests currently inside the server (queued or "
            "executing).",
            model,
            registry=registry,
        )
        self.stage_cpu = Histogram(
            "tpu_request_cpu_seconds",
            "Thread-CPU seconds a request spent in each named server "
            "stage (frontend_decode/queue_wait/batch_assembly/device_put/"
            "compute/readback/package/encode, plus rpc for non-inference "
            "methods). Populated only while stage-CPU accounting is "
            "enabled (POST /v2/debug/profiling {\"stage_cpu\": true}).",
            ("stage",),
            buckets=STAGE_CPU_BUCKETS_S,
            registry=registry,
        )
        # hot-path cache: stage -> histogram child, so observe_stage_cpu
        # skips the family-lock labels() lookup per booking
        from client_tpu.observability.profiling import STAGES

        self._stage_children = {
            stage: self.stage_cpu.labels(stage) for stage in STAGES
        }
        self.queue_rejected = Counter(
            "tpu_queue_rejected_total",
            "Requests rejected by admission control, by reason "
            "(queue_full = max_queue_size hit, timeout = queue deadline "
            "passed before execution).",
            ("model", "reason"),
            registry=registry,
        )
        self.queue_depth = Gauge(
            "tpu_queue_depth",
            "Requests waiting in the scheduler queue, per priority level "
            "(level 1 = highest priority).",
            ("model", "level"),
            registry=registry,
        )
        self.drain_rejected = Counter(
            "tpu_drain_rejected_total",
            "Requests rejected because the server was draining or "
            "stopped (clean 503/UNAVAILABLE, load balancers should have "
            "routed elsewhere).",
            model,
            registry=registry,
        )
        self.server_state = Gauge(
            "tpu_server_state",
            "Lifecycle state of the server (0 = serving, 1 = draining, "
            "2 = stopped, 3 = recovering — an engine reload is in "
            "flight while the lifecycle itself keeps serving).",
            registry=registry,
        )
        # self-healing (PR 20): one counter/histogram pair covers every
        # supervision tier — tier="engine" (auto reload), "pod" (member
        # respawn + mesh re-init), "fleet" (replica replacement)
        self.recovery_total = Counter(
            "tpu_recovery_total",
            "Completed automatic recoveries by supervision tier "
            "(engine / pod / fleet) and outcome (success / failed).",
            ("tier", "outcome"),
            registry=registry,
        )
        self.recovery_seconds = Histogram(
            "tpu_recovery_seconds",
            "Detected-failure-to-serving-again duration (MTTR) per "
            "completed recovery, by supervision tier.",
            ("tier",),
            buckets=DURATION_BUCKETS_S,
            registry=registry,
        )
        self.frontend_errors = Counter(
            "tpu_frontend_request_errors",
            "Requests rejected by a front-end before reaching the engine "
            "(malformed payloads; not counted by the statistics extension).",
            ("protocol",),
            registry=registry,
        )
        self.codec_fastpath = Counter(
            "tpu_codec_fastpath_total",
            "ModelInfer wire-codec fast-path outcomes: 'hit' requests "
            "decoded by the protobuf-free scanner, 'fallback' requests "
            "outside the fast shape (parsed by the proto codec), "
            "'encode_fallback' responses the hand-rolled encoder "
            "declined.",
            ("outcome",),
            registry=registry,
        )
        self._codec_children = {
            outcome: self.codec_fastpath.labels(outcome)
            for outcome in ("hit", "fallback", "encode_fallback")
        }
        self.shm_ring_slots = Gauge(
            "tpu_shm_ring_slots_in_use",
            "Ring slots currently owned by the server (request read, "
            "response not yet written), per registered ring region.",
            ("region",),
            registry=registry,
        )
        self.duty_cycle = Gauge(
            "tpu_duty_cycle",
            "Fraction of wall time the device spent executing models since "
            "the previous scrape.",
            registry=registry,
        )
        self.device_compute_ns = Counter(
            "tpu_device_compute_ns_total",
            "Cumulative nanoseconds of device model execution, per device "
            "(monotone; derive per-device duty cycle from deltas). A "
            "sharded model's SPMD execution credits every device of its "
            "mesh; unsharded models credit their default device.",
            ("device",),
            registry=registry,
        )
        self.device_memory = Gauge(
            "tpu_device_memory_bytes",
            "Device memory in use per device (jax memory_stats "
            "bytes_in_use; 0 when the backend reports no accounting, "
            "e.g. the CPU mesh).",
            ("device",),
            registry=registry,
        )
        self.memory_used = Gauge(
            "tpu_memory_used_bytes",
            "Device memory in use, per local device.",
            ("device",),
            registry=registry,
        )
        self.memory_limit = Gauge(
            "tpu_memory_limit_bytes",
            "Device memory capacity, per local device.",
            ("device",),
            registry=registry,
        )
        self.memory_utilization = Gauge(
            "tpu_memory_utilization",
            "Used / limit device memory fraction, per local device.",
            ("device",),
            registry=registry,
        )
        # Pre-registry wire names, kept so existing scrape configs and the
        # round-1 dashboards survive the rewrite (statistics mirrors).
        self.legacy_count = Counter(
            "tpu_inference_count",
            "Successful inference requests.",
            model,
            registry=registry,
        )
        self.legacy_duration_ns = Counter(
            "tpu_inference_duration_ns",
            "Cumulative successful-request nanoseconds.",
            model,
            registry=registry,
        )
        self.legacy_fail_count = Counter(
            "tpu_inference_fail_count",
            "Failed inference requests.",
            model,
            registry=registry,
        )
        # Live telemetry (observability.slo): rolling-window latency
        # sketches + SLO error-budget tracking, fed from the SAME
        # observe_success/observe_failure events as the histograms above,
        # so the live signals and the cumulative ones can never disagree
        # about what happened — only about when.
        self.telemetry = LiveTelemetry(
            buckets=DURATION_BUCKETS_S,
            clock_ns=clock_ns,
            objective_resolver=self._resolve_objective,
        )
        self.rolling_latency = Gauge(
            "tpu_rolling_latency_seconds",
            "Rolling-window latency quantile per model (sliding sub-window "
            "sketch over the duration bucket grid; window=30s/5m, "
            "quantile=0.5/0.95/0.99). Reflects the window, not the "
            "server's lifetime.",
            ("model", "window", "quantile"),
            registry=registry,
        )
        self.slo_burn_rate = Gauge(
            "tpu_slo_latency_burn_rate",
            "Error-budget burn rate over the model's SLO window: the "
            "fraction of requests violating the SLO (failed or over the "
            "latency target) divided by the allowed fraction "
            "(1 - availability). 1.0 = burning exactly the budget; only "
            "models declaring an slo config report.",
            model,
            registry=registry,
        )
        self.slo_budget_remaining = Gauge(
            "tpu_slo_error_budget_remaining",
            "Fraction of the model's rolling-window error budget still "
            "unspent (1.0 = no violations, 0.0 = budget exhausted).",
            model,
            registry=registry,
        )
        # LLM engine families (client_tpu.llm): paged KV-cache occupancy
        # and continuous-batching behavior. The blocks gauges are the
        # capacity-admission signal — in_use returning to zero after any
        # mix of completed/cancelled/expired generations is the engine's
        # no-leak invariant (asserted in tests/test_llm_engine.py).
        self.kv_blocks_in_use = Gauge(
            "tpu_kv_blocks_in_use",
            "Paged KV-cache blocks currently owned by live sequences.",
            model,
            registry=registry,
        )
        self.kv_blocks_total = Gauge(
            "tpu_kv_blocks_total",
            "Allocatable paged KV-cache blocks in the engine's pool "
            "(the reserved trash block excluded).",
            model,
            registry=registry,
        )
        self.kv_blocks_shared = Gauge(
            "tpu_kv_blocks_shared",
            "Physical KV blocks referenced by more than one live "
            "sequence (copy-on-write prefix sharing).",
            model,
            registry=registry,
        )
        self.prefix_cache_hits = Counter(
            "tpu_prefix_cache_hits_total",
            "Prompt blocks served from the shared prefix index instead "
            "of being prefilled (each hit skips one block of prefill "
            "compute and memory).",
            model,
            registry=registry,
        )
        self.llm_active_sequences = Gauge(
            "tpu_llm_active_sequences",
            "Sequences in the engine's running decode batch.",
            model,
            registry=registry,
        )
        self.llm_waiting_sequences = Gauge(
            "tpu_llm_waiting_sequences",
            "Sequences queued for admission (cache or batch capacity).",
            model,
            registry=registry,
        )
        self.llm_step_batch = Histogram(
            "tpu_llm_step_batch_size",
            "Sequences decoded per continuous-batching step (each step "
            "generates one token per member).",
            model,
            buckets=BATCH_SIZE_BUCKETS,
            registry=registry,
        )
        self.llm_preemptions = Counter(
            "tpu_llm_preemptions_total",
            "Sequences preempted (blocks reclaimed, re-queued) because "
            "the KV block pool ran dry mid-decode.",
            model,
            registry=registry,
        )
        self.llm_generated_tokens = Counter(
            "tpu_llm_generated_tokens_total",
            "Tokens generated by the LLM engine (prefill first-tokens "
            "included).",
            model,
            registry=registry,
        )
        # Speculative decoding (PR-15): proposed/accepted drive the
        # acceptance rate, and the per-sequence tokens-per-verify-step
        # distribution is the direct read of how much each multi-query
        # call bought (1 = nothing accepted, K+1 = the whole draft).
        self.llm_spec_proposed = Counter(
            "tpu_llm_spec_proposed_total",
            "Draft tokens submitted to speculative verification "
            "(post-clamp: only candidates a verify step actually "
            "carried).",
            model,
            registry=registry,
        )
        self.llm_spec_accepted = Counter(
            "tpu_llm_spec_accepted_total",
            "Draft tokens accepted by speculative verification (each "
            "one a decode step the engine did not have to run).",
            model,
            registry=registry,
        )
        self.llm_spec_tokens_per_step = Histogram(
            "tpu_llm_spec_tokens_per_step",
            "Tokens one sequence emitted per speculative verify step "
            "(accepted drafts + the sampled correction/bonus token).",
            model,
            buckets=SPEC_TOKENS_BUCKETS,
            registry=registry,
        )
        # Pod-scale serving: one row per pod member process. Exported by
        # the coordinator (the only member running front-ends) from step
        # bus acks — workers have no metrics endpoint of their own.
        self.pod_process_up = Gauge(
            "tpu_pod_process_up",
            "Pod member liveness: 1 while the process acks step "
            "broadcasts (process 0 is the coordinator itself), 0 once "
            "the bus declares it lost.",
            ("process",),
            registry=registry,
        )
        self.pod_process_duty = Gauge(
            "tpu_pod_process_duty_ratio",
            "Fraction of wall time each pod member spent executing "
            "device steps since the pod came up (workers report "
            "cumulative busy nanoseconds in their step acks).",
            ("process",),
            registry=registry,
        )
        self._duty_lock = threading.Lock()
        # First scrape reports utilization since server start — not 0.0
        # (the pre-registry handler's first-scrape blind spot).
        self._duty_prev = (self._clock_ns(), 0)
        registry.add_collect_hook(self._collect)

    # -- hot-path hooks (called by ServerCore's execution paths) ------------

    def _resolve_objective(self, model_name: str):
        """The model's declared SLO (repository config ``slo`` attr);
        None when it declares none or is unknown. A malformed declaration
        resolves to None but emits a rate-limited warning — a typo'd SLO
        silently tracking nothing would look exactly like a healthy
        model with no objective."""
        try:
            model = self.core.repository.peek(model_name)
        except Exception:  # noqa: BLE001 - telemetry must not fail requests
            return None
        if model is None:
            return None
        try:
            return SloObjective.from_model(model)
        except ValueError as e:
            logger = getattr(self.core, "logger", None)
            if logger is not None:
                logger.warning(
                    "slo_declaration_invalid",
                    model=model_name,
                    error=str(e),
                    rate_key=("slo_declaration_invalid", model_name),
                )
            return None

    def observe_success(
        self, model: str, queue_ns: int, compute_ns: int, total_ns: int,
        count: int = 1, trace_id: str = "",
    ) -> None:
        """Book ``count`` successful requests (per-request durations; the
        merged direct path passes its chunk average with count=n).
        ``trace_id`` (when the request was traced) becomes the duration
        histogram's OpenMetrics exemplar, linking ``/metrics`` buckets to
        ``/v2/debug/requests`` evidence."""
        total_s = total_ns / 1e9
        self.request_success.labels(model).inc(count)
        self.request_duration.labels(model).observe(
            total_s,
            count,
            exemplar=({"trace_id": trace_id}, total_s) if trace_id else None,
        )
        self.queue_duration.labels(model).observe(queue_ns / 1e9, count)
        self.compute_duration.labels(model).observe(compute_ns / 1e9, count)
        self.telemetry.record(model, total_s, ok=True, count=count)

    def observe_failure(self, model: str, count: int = 1) -> None:
        self.request_failure.labels(model).inc(count)
        self.telemetry.record(model, 0.0, ok=False, count=count)

    def observe_execution(self, model: str, rows: int) -> None:
        """Book one device execution of ``rows`` merged rows."""
        self.batch_size.labels(model).observe(float(rows))

    def observe_frontend_error(self, protocol: str) -> None:
        self.frontend_errors.labels(protocol).inc()

    def observe_stage_cpu(self, stage: str, cpu_ns: int, count: int = 1) -> None:
        """Book ``count`` requests' thread-CPU for one stage (merged
        batch paths pass their chunk total with count=n; the histogram
        records the per-request average n times so _sum stays the true
        total and _count the true request count)."""
        if count <= 0:
            return
        child = self._stage_children.get(stage)
        if child is None:
            child = self._stage_children[stage] = self.stage_cpu.labels(stage)
        child.observe(cpu_ns / count / 1e9, count)

    def observe_codec(self, outcome: str) -> None:
        """Book one wire-codec fast-path outcome (children precached —
        this rides the per-request decode path)."""
        child = self._codec_children.get(outcome)
        if child is None:
            child = self._codec_children[outcome] = self.codec_fastpath.labels(
                outcome
            )
        child.inc()

    def set_ring_slots(self, region: str, value: int) -> None:
        """Publish a ring region's in-flight slot count (exact at every
        read/complete transition, not sampled at scrape time)."""
        self.shm_ring_slots.labels(region).set(value)

    def remove_ring_region(self, region: str) -> None:
        """Drop an unregistered ring's gauge child — ring names rotate
        per client run, so pruning keeps /metrics cardinality bounded by
        the LIVE ring set, not history."""
        self.shm_ring_slots.remove(region)

    def observe_rejection(self, model: str, reason: str) -> None:
        """Book one admission-control rejection (queue_full / timeout)."""
        self.queue_rejected.labels(model, reason).inc()

    def observe_drain_rejection(self, model: str) -> None:
        """Book one request rejected by the lifecycle drain gate."""
        self.drain_rejected.labels(model or "").inc()

    def set_queue_depth(self, model: str, depths) -> None:
        """Publish the scheduler queue depth per priority level (fed from
        the same submit/take/expire events that stamp the statistics
        extension's queue timings)."""
        for level, depth in depths.items():
            self.queue_depth.labels(model, str(level)).set(depth)

    # -- LLM engine hooks (client_tpu.llm.engine) ---------------------------

    def set_kv_blocks(
        self, model: str, in_use: int, total: int, shared: int = 0
    ) -> None:
        """Publish the paged KV-cache occupancy (the engine calls this on
        every allocation-state change, not at scrape time, so the gauge
        is exact the moment a sequence completes or is cancelled)."""
        self.kv_blocks_in_use.labels(model).set(in_use)
        self.kv_blocks_total.labels(model).set(total)
        self.kv_blocks_shared.labels(model).set(shared)

    def observe_prefix_hits(self, model: str, blocks: int = 1) -> None:
        """Book prompt blocks matched in the shared prefix index (their
        prefill was skipped)."""
        self.prefix_cache_hits.labels(model).inc(blocks)

    def set_llm_sequences(self, model: str, active: int, waiting: int) -> None:
        self.llm_active_sequences.labels(model).set(active)
        self.llm_waiting_sequences.labels(model).set(waiting)

    def set_pod_process(self, process: int, up: bool, duty: float) -> None:
        """One pod member's liveness + duty split (coordinator-side)."""
        label = str(process)
        self.pod_process_up.labels(label).set(1 if up else 0)
        self.pod_process_duty.labels(label).set(max(0.0, min(1.0, duty)))

    def prune_pod_process(self, process: int) -> None:
        """Drop one pod member's gauge children (the member was replaced
        or the pod shut down) — without this, a respawned member's stale
        twin lingers at its last value forever, exactly the SLO-gauge
        leak PR 8 fixed."""
        label = str(process)
        self.pod_process_up.remove(label)
        self.pod_process_duty.remove(label)

    def observe_recovery(self, tier: str, outcome: str, seconds: float) -> None:
        """Book one completed automatic recovery (any supervision tier);
        ``seconds`` is detection-to-serving-again — the MTTR sample."""
        self.recovery_total.labels(tier, outcome).inc()
        self.recovery_seconds.labels(tier).observe(max(0.0, seconds))

    def observe_llm_step(self, model: str, batch_size: int) -> None:
        """Book one continuous-batching decode step (per-step batch-size
        distribution; tokens are booked separately via
        :meth:`observe_llm_tokens` so cancelled lanes never count)."""
        self.llm_step_batch.labels(model).observe(batch_size)

    def observe_llm_tokens(self, model: str, count: int = 1) -> None:
        """Book generated-and-streamed tokens (prefill first tokens and
        per-step emissions)."""
        self.llm_generated_tokens.labels(model).inc(count)

    def observe_llm_preemption(self, model: str) -> None:
        self.llm_preemptions.labels(model).inc()

    def observe_llm_speculation(
        self, model: str, proposed: int, accepted: int, lane_tokens
    ) -> None:
        """Book one speculative verify step: drafts verified/accepted
        across the batch, plus each live lane's emitted-token count for
        the tokens-per-step histogram."""
        if proposed:
            self.llm_spec_proposed.labels(model).inc(proposed)
        if accepted:
            self.llm_spec_accepted.labels(model).inc(accepted)
        child = self.llm_spec_tokens_per_step.labels(model)
        for tokens in lane_tokens:
            child.observe(tokens)

    def pending_inc(self, model: str, count: int = 1) -> None:
        self.pending_requests.labels(model).inc(count)

    def pending_dec(self, model: str, count: int = 1) -> None:
        self.pending_requests.labels(model).dec(count)

    # -- scrape -------------------------------------------------------------

    def render(self, exemplars: bool = False) -> str:
        """The exposition document (runs the collect hook below).
        ``exemplars=True`` appends OpenMetrics exemplars (trace id +
        latency) to duration-histogram bucket samples that carry one;
        the default text format is unchanged."""
        return self.registry.render(exemplars=exemplars)

    def _collect(self) -> None:
        """Scrape-time refresh: exactly ONE statistics snapshot feeds the
        mirror counters (counters and derived values stay consistent
        within a scrape), plus duty cycle and device memory."""
        stats = self.core.statistics()
        for ms in stats["model_stats"]:
            name = ms["name"]
            inference = ms["inference_stats"]
            self.legacy_count.labels(name).set(inference["success"]["count"])
            self.legacy_duration_ns.labels(name).set(
                inference["success"]["ns"]
            )
            self.legacy_fail_count.labels(name).set(inference["fail"]["count"])
        lifecycle = getattr(self.core, "lifecycle", None)
        if lifecycle is not None:
            from client_tpu.lifecycle import RECOVERING, SERVING, STATE_VALUES

            state = lifecycle.state
            if state == SERVING and getattr(self.core, "recovering", False):
                # self-healing overlay: an engine reload in flight while
                # the lifecycle keeps serving — operators watching the
                # gauge see the recovery window, probes see ready
                state = RECOVERING
            self.server_state.set(
                float(STATE_VALUES.get(state, 0))
            )
        busy_ns = self.core.device_busy_ns_total
        now_ns = self._clock_ns()
        with self._duty_lock:
            prev_ns, prev_busy = self._duty_prev
            self._duty_prev = (now_ns, busy_ns)
        duty = 0.0
        if now_ns > prev_ns:
            duty = min(1.0, max(0, busy_ns - prev_busy) / (now_ns - prev_ns))
        self.duty_cycle.set(duty)
        # per-device split of the same monotone counter (sharded models
        # credit every mesh device); before any device execution the
        # default device exports 0 so the family always renders
        by_device = getattr(self.core, "device_busy_by_device", None)
        per_device = by_device() if callable(by_device) else {}
        if not per_device:
            # pre-execution: export the default device's label (the same
            # one add_busy_ns will credit) so no stale "0" child lingers
            # on hosts whose first device id is nonzero
            default = getattr(self.core, "_default_device_label_value", None)
            label = default() if callable(default) else "0"
            per_device = {label: busy_ns}
        for device, ns in per_device.items():
            self.device_compute_ns.labels(device).set(ns)
        # rolling quantiles + SLO burn gauges reflect the window at
        # scrape time, not the hot path (one O(buckets) merge per model)
        self.telemetry.collect(
            self.rolling_latency,
            self.slo_burn_rate,
            self.slo_budget_remaining,
        )
        self._collect_memory()

    def _collect_memory(self) -> None:
        if self._jax is None:
            return
        try:
            devices = self._jax.local_devices()
        except Exception:  # noqa: BLE001 - no backend available
            return
        for i, device in enumerate(devices):
            try:
                mstats = device.memory_stats() or {}
            except Exception:  # noqa: BLE001 - backend-dependent
                mstats = {}
            used = mstats.get("bytes_in_use")
            limit = mstats.get("bytes_limit") or mstats.get(
                "bytes_reservable_limit"
            )
            # per-device memory family (device-id labels, matching
            # tpu_device_compute_ns_total): 0 when the backend has no
            # accounting so every device still reports a sample
            self.device_memory.labels(str(getattr(device, "id", i))).set(
                float(used) if used is not None else 0.0
            )
            if used is not None:
                self.memory_used.labels(str(i)).set(used)
            if limit:
                self.memory_limit.labels(str(i)).set(limit)
                if used is not None:
                    self.memory_utilization.labels(str(i)).set(used / limit)
