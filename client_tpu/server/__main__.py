"""CLI entry point: ``python -m client_tpu.server``.

Starts the KServe v2 HTTP + gRPC front-ends with the built-in fixture models
and (optionally) a model repository directory of ``<name>/model.py`` models.
"""

import argparse
import asyncio


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="client_tpu.server",
        description="TPU-native KServe v2 inference server (JAX backend)",
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--grpc-port", type=int, default=8001)
    parser.add_argument(
        "--model-repository",
        default=None,
        help="directory of <name>/model.py models (python_backend analogue)",
    )
    parser.add_argument(
        "--no-builtin-models",
        action="store_true",
        help="skip the built-in fixture models (simple, identity_*, repeat)",
    )
    parser.add_argument(
        "--zoo-models",
        action="store_true",
        help="also register the model-zoo adapters (resnet, llm_decode)",
    )
    parser.add_argument(
        "--max-workers", type=int, default=32, help="model execution threads"
    )
    parser.add_argument(
        "--drain-timeout",
        type=float,
        default=30.0,
        help="graceful-shutdown budget in seconds: on SIGTERM/SIGINT the "
        "server flips /v2/health/ready to 503 (liveness stays up), "
        "rejects new inferences with 503/UNAVAILABLE, and waits this "
        "long for in-flight and queued work before closing — the "
        "rolling-restart contract load balancers rely on",
    )
    parser.add_argument(
        "--platform",
        default=None,
        help="force the JAX platform (e.g. 'cpu', 'tpu'); overrides any "
        "site default — useful for dev loops on hosts where the default "
        "platform is a remote TPU relay",
    )
    parser.add_argument(
        "--grpc-frontend",
        choices=["native", "aio", "auto"],
        default="auto",
        help="gRPC front-end implementation: 'native' (C++ h2 server, the "
        "fast path), 'aio' (grpc.aio), 'auto' = native when built",
    )
    parser.add_argument(
        "--grpc-tls-cert",
        default=None,
        help="PEM certificate chain: the native gRPC front-end terminates "
        "TLS itself (grpcs, ALPN h2); requires --grpc-tls-key",
    )
    parser.add_argument(
        "--grpc-tls-key",
        default=None,
        help="PEM private key for --grpc-tls-cert",
    )
    args = parser.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from client_tpu.server.core import ServerCore
    from client_tpu.server.model_repository import build_repository

    repository = build_repository(
        args.model_repository,
        builtin=not args.no_builtin_models,
        zoo=args.zoo_models,
    )
    core = ServerCore(repository, max_workers=args.max_workers)

    async def serve() -> None:
        from client_tpu.server.http_server import serve_http

        impl = args.grpc_frontend
        if impl == "auto":
            from client_tpu.server.native_frontend import native_available

            impl = "native" if native_available() else "aio"

        http_runner = await serve_http(core, args.host, args.http_port)
        native_frontend = None
        grpc_server = None
        if impl == "native":
            from client_tpu.server.native_frontend import serve_grpc_native

            native_frontend, grpc_port = await serve_grpc_native(
                core,
                args.host,
                args.grpc_port,
                tls_cert=args.grpc_tls_cert,
                tls_key=args.grpc_tls_key,
            )
        else:
            if args.grpc_tls_cert:
                raise SystemExit(
                    "--grpc-tls-cert requires the native gRPC front-end"
                )
            from client_tpu.server.grpc_server import serve_grpc

            grpc_server, grpc_port = await serve_grpc(
                core, args.host, args.grpc_port
            )
        # Lifecycle events go through the structured logger (JSON lines
        # on stderr by default, the log_file setting elsewhere) so
        # orchestrators can parse them instead of scraping prose.
        core.logger.info(
            "server_started",
            host=args.host,
            http_port=http_runner.addresses[0][1],
            grpc_port=grpc_port,
            grpc_frontend=impl,
        )
        import signal

        stop_event = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_event.set)
            except NotImplementedError:  # pragma: no cover - non-POSIX
                pass
        try:
            await stop_event.wait()
        finally:
            # Graceful half first: readiness false + reject new work while
            # in-flight and queued requests finish inside --drain-timeout;
            # only then do the front-ends close. core.drain() emits the
            # drain_started / drain_deadline_expired / drain_completed
            # events through the structured logger itself.
            drained = await core.drain(args.drain_timeout)
            core.logger.info("server_stopping", drained=drained)
            if native_frontend is not None:
                native_frontend.stop()
            if grpc_server is not None:
                await grpc_server.stop(grace=2)
            await http_runner.cleanup()

    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
