"""KServe v2 gRPC front-end (grpc.aio) over :class:`ServerCore`.

Implements inference.GRPCInferenceService including decoupled
``ModelStreamInfer`` (one stream, many responses per request — the token
streaming path) and the system/TPU shared-memory registration RPCs.

The non-inference methods are implemented once in
:mod:`client_tpu.server._grpc_codec` (shared with the native C++ h2
front-end); this module binds them into grpc.aio and keeps only the
inference request/response tensor conversion local.
"""


import asyncio

import grpc
import numpy as np

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._service_stubs import (
    GRPCInferenceServiceServicer,
    add_GRPCInferenceServiceServicer_to_server,
)
from client_tpu.server import _grpc_codec as codec
from client_tpu.server.core import (
    CoreRequest,
    CoreRequestedOutput,
    CoreResponse,
    ServerCore,
)
from client_tpu.utils import (
    InferenceServerException,
    serialize_byte_tensor,
)

MAX_GRPC_MESSAGE_SIZE = 2**31 - 1  # INT32_MAX, both directions

_INT_TO_STATUS_CODE = {
    code.value[0]: code for code in grpc.StatusCode if code.value
}


def _status_for(message: str, exc=None) -> grpc.StatusCode:
    """Status for an inference failure. Admission rejections carry their
    code directly (``grpc_code``): queue-full -> RESOURCE_EXHAUSTED,
    queue timeout -> DEADLINE_EXCEEDED."""
    return _INT_TO_STATUS_CODE.get(
        codec.status_code_for(message, exc=exc),
        grpc.StatusCode.INVALID_ARGUMENT,
    )


_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


def build_core_request(core: ServerCore, request: pb.ModelInferRequest) -> CoreRequest:
    core_request = CoreRequest(
        model_name=request.model_name,
        model_version=request.model_version,
        id=request.id,
        parameters=codec.params_to_dict(request.parameters),
    )
    # raw_input_contents entries are consumed in order by the inputs that
    # are NOT sourced from shared memory (Triton semantics: shm inputs
    # contribute no raw entry).
    n_raw = len(request.raw_input_contents)
    raw_index = 0
    for tensor in request.inputs:
        params = codec.params_to_dict(tensor.parameters)
        shm_region = params.get("shared_memory_region")
        raw = None
        json_data = None
        if shm_region is not None:
            pass
        elif raw_index < n_raw:
            raw = request.raw_input_contents[raw_index]
            raw_index += 1
        elif tensor.HasField("contents"):
            field = _CONTENTS_FIELD.get(tensor.datatype)
            if field is None:
                raise InferenceServerException(
                    f"datatype '{tensor.datatype}' has no proto contents "
                    "representation; use raw_input_contents"
                )
            json_data = list(getattr(tensor.contents, field))
        core_request.inputs.append(
            core.decode_input(
                tensor.name,
                tensor.datatype,
                list(tensor.shape),
                raw=raw,
                json_data=json_data,
                shm_region=shm_region,
                shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                shm_offset=int(params.get("shared_memory_offset", 0)),
            )
        )
    if raw_index != n_raw:
        raise InferenceServerException(
            f"raw_input_contents has {n_raw} entries but only "
            f"{raw_index} non-shared-memory inputs consumed them"
        )
    for out in request.outputs:
        params = codec.params_to_dict(out.parameters)
        core_request.outputs.append(
            CoreRequestedOutput(
                name=out.name,
                classification=int(params.get("classification", 0)),
                shm_region=params.get("shared_memory_region"),
                shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                shm_offset=int(params.get("shared_memory_offset", 0)),
            )
        )
    return core_request


def build_proto_response(core_response: CoreResponse) -> pb.ModelInferResponse:
    response = pb.ModelInferResponse(
        model_name=core_response.model_name,
        model_version=core_response.model_version,
        id=core_response.id,
    )
    codec.dict_to_params(core_response.parameters, response.parameters)
    for tensor in core_response.outputs:
        out = response.outputs.add(
            name=tensor.name,
            datatype=tensor.datatype,
            shape=tensor.shape,
        )
        if tensor.name in core_response.shm_outputs:
            region, size, offset = core_response.shm_outputs[tensor.name]
            out.parameters["shared_memory_region"].string_param = region
            out.parameters["shared_memory_byte_size"].int64_param = size
            if offset:
                out.parameters["shared_memory_offset"].int64_param = offset
            response.raw_output_contents.append(b"")
        elif tensor.datatype == "BYTES":
            response.raw_output_contents.append(
                serialize_byte_tensor(tensor.data).tobytes()
            )
        else:
            response.raw_output_contents.append(
                np.ascontiguousarray(tensor.data).tobytes()
            )
    return response


def _delegated(method_name: str):
    async def handler(self, request, context):
        await self._chaos_gate(context, method_name)
        try:
            return codec.handle_method(self.core, method_name, request)
        except codec.RpcError as e:
            await context.abort(
                _INT_TO_STATUS_CODE.get(e.status, grpc.StatusCode.UNKNOWN),
                e.message,
            )

    handler.__name__ = method_name
    return handler


class _Servicer(GRPCInferenceServiceServicer):
    def __init__(self, core: ServerCore, chaos=None):
        self.core = core
        self.chaos = chaos

    async def _chaos_gate(self, context, method: str) -> None:
        """Fault injection (ChaosPolicy): added latency plus injected
        UNAVAILABLE aborts — every drawn fate (error/reset/truncate)
        maps to an UNAVAILABLE abort, the HTTP/2 face of a dying host."""
        if self.chaos is None or not self.chaos.applies_to(method):
            return
        if self.chaos.latency_s:
            await asyncio.sleep(self.chaos.latency_s)
        fate = self.chaos.draw()
        if fate is not None:
            self.chaos.record(fate)
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, "chaos: injected unavailability"
            )

    # -- inference -----------------------------------------------------------

    def _begin_trace(self, context, request):
        """Trace sampling + W3C traceparent extraction from the call
        metadata (the gRPC face of the HTTP header)."""
        metadata = dict(context.invocation_metadata() or ())
        return self.core.trace_manager.begin(
            request.model_name,
            model_version=request.model_version,
            traceparent=metadata.get("traceparent"),
            request_id=request.id,
        )

    async def ModelInfer(self, request, context):
        await self._chaos_gate(context, "ModelInfer")
        trace = self._begin_trace(context, request)
        prof = self.core.profiling
        # one take() covers this request's decode AND encode brackets
        measured = prof.take()
        try:
            # drain fast path: UNAVAILABLE before paying decode cost
            # (outside the inner try: a drain rejection is booked on its
            # own counter, not as a malformed-request frontend error)
            self.core.reject_if_draining(request.model_name)
            try:
                if measured:
                    decode_cpu0 = prof.cpu_now()
                    core_request = build_core_request(self.core, request)
                    prof.account(
                        "frontend_decode", prof.cpu_now() - decode_cpu0
                    )
                else:
                    core_request = build_core_request(self.core, request)
            except InferenceServerException:
                # rejected before reaching the engine: the statistics
                # extension never sees it, the front-end counter does
                # (same family the HTTP front-end books, protocol label
                # apart — the shared registry keeps both faces consistent)
                self.core.metrics.observe_frontend_error("grpc")
                raise
            core_request.trace = trace
            core_response = await self.core.infer(core_request)
        except InferenceServerException as e:
            if trace is not None:
                trace.end(error=e.message())
            log = self.core.logger
            if log.verbose_hot:
                log.verbose(
                    "request",
                    model=request.model_name,
                    protocol="grpc",
                    status="error",
                    error=e.message(),
                )
            await context.abort(_status_for(e.message(), e), e.message())
        except BaseException as e:
            if trace is not None:
                trace.end(error=str(e))
            raise
        if trace is not None:
            trace.end()
        log = self.core.logger
        if log.verbose_hot:
            log.verbose(
                "request",
                model=request.model_name,
                protocol="grpc",
                status="ok",
                request_id=request.id,
            )
        if measured:
            encode_cpu0 = prof.cpu_now()
            response = build_proto_response(core_response)
            prof.account("encode", prof.cpu_now() - encode_cpu0)
            return response
        return build_proto_response(core_response)

    async def ModelStreamInfer(self, request_iterator, context):
        async for request in request_iterator:
            # an injected fault aborts the whole stream with UNAVAILABLE
            # (connection-loss semantics), not a per-request error reply
            await self._chaos_gate(context, "ModelStreamInfer")
            trace = self._begin_trace(context, request)
            prof = self.core.profiling
            try:
                # drain-aware: rejected stream requests surface as clean
                # in-band errors, never cancelled streams
                self.core.reject_if_draining(request.model_name)
                try:
                    if prof.take():
                        decode_cpu0 = prof.cpu_now()
                        core_request = build_core_request(self.core, request)
                        prof.account(
                            "frontend_decode", prof.cpu_now() - decode_cpu0
                        )
                    else:
                        core_request = build_core_request(self.core, request)
                except InferenceServerException:
                    self.core.metrics.observe_frontend_error("grpc")
                    raise
                core_request.trace = trace
                async for core_response in self.core.infer_decoupled(
                    core_request
                ):
                    if prof.take():
                        encode_cpu0 = prof.cpu_now()
                        wire_response = build_proto_response(core_response)
                        prof.account("encode", prof.cpu_now() - encode_cpu0)
                    else:
                        wire_response = build_proto_response(core_response)
                    yield pb.ModelStreamInferResponse(
                        infer_response=wire_response
                    )
            except InferenceServerException as e:
                if trace is not None:
                    trace.end(error=e.message())
                    trace = None
                log = self.core.logger
                if log.verbose_hot:
                    log.verbose(
                        "request",
                        model=request.model_name,
                        protocol="grpc",
                        status="error",
                        error=e.message(),
                        streaming=True,
                    )
                error = pb.ModelStreamInferResponse(
                    error_message=e.message(),
                    infer_response=pb.ModelInferResponse(id=request.id),
                )
                yield error
            except BaseException as e:
                # stream teardown (client cancel) or a non-ISE failure:
                # the trace record must still be exported
                if trace is not None:
                    trace.end(error=str(e) or type(e).__name__)
                raise
            if trace is not None:
                trace.end()


# Bind every non-inference method to the shared codec implementation.
for _method in codec.METHODS:
    setattr(_Servicer, _method, _delegated(_method))


async def serve_grpc(
    core: ServerCore, host: str = "0.0.0.0", port: int = 8001, chaos=None
):
    """Start the gRPC server; returns (server, bound_port).

    ``chaos`` (a :class:`client_tpu.resilience.ChaosPolicy`) enables
    fault injection for resilience testing."""
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
            ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
        ]
    )
    add_GRPCInferenceServiceServicer_to_server(
        _Servicer(core, chaos=chaos), server
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server, bound
