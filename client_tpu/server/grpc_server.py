"""KServe v2 gRPC front-end (grpc.aio) over :class:`ServerCore`.

Implements inference.GRPCInferenceService including decoupled
``ModelStreamInfer`` (one stream, many responses per request — the token
streaming path) and the system/TPU shared-memory registration RPCs.

The non-inference methods are implemented once in
:mod:`client_tpu.server._grpc_codec` (shared with the native C++ h2
front-end); this module binds them into grpc.aio and keeps only the
inference request/response tensor conversion local.
"""


import asyncio

import grpc
import numpy as np

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._service_stubs import (
    GRPCInferenceServiceServicer,
    add_GRPCInferenceServiceServicer_to_server,
)
from client_tpu.server import _grpc_codec as codec
from client_tpu.server import shm_ring
from client_tpu.server.core import (
    CoreRequest,
    CoreRequestedOutput,
    CoreResponse,
    ServerCore,
)
from client_tpu.utils import (
    InferenceServerException,
    serialize_byte_tensor,
)

MAX_GRPC_MESSAGE_SIZE = 2**31 - 1  # INT32_MAX, both directions

_INT_TO_STATUS_CODE = {
    code.value[0]: code for code in grpc.StatusCode if code.value
}


def _status_for(message: str, exc=None) -> grpc.StatusCode:
    """Status for an inference failure. Admission rejections carry their
    code directly (``grpc_code``): queue-full -> RESOURCE_EXHAUSTED,
    queue timeout -> DEADLINE_EXCEEDED."""
    return _INT_TO_STATUS_CODE.get(
        codec.status_code_for(message, exc=exc),
        grpc.StatusCode.INVALID_ARGUMENT,
    )


_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


def build_core_request(core: ServerCore, request: pb.ModelInferRequest) -> CoreRequest:
    core_request = CoreRequest(
        model_name=request.model_name,
        model_version=request.model_version,
        id=request.id,
        parameters=codec.params_to_dict(request.parameters),
    )
    # raw_input_contents entries are consumed in order by the inputs that
    # are NOT sourced from shared memory (Triton semantics: shm inputs
    # contribute no raw entry).
    n_raw = len(request.raw_input_contents)
    raw_index = 0
    for tensor in request.inputs:
        params = codec.params_to_dict(tensor.parameters)
        shm_region = params.get("shared_memory_region")
        raw = None
        json_data = None
        if shm_region is not None:
            pass
        elif raw_index < n_raw:
            raw = request.raw_input_contents[raw_index]
            raw_index += 1
        elif tensor.HasField("contents"):
            field = _CONTENTS_FIELD.get(tensor.datatype)
            if field is None:
                raise InferenceServerException(
                    f"datatype '{tensor.datatype}' has no proto contents "
                    "representation; use raw_input_contents"
                )
            json_data = list(getattr(tensor.contents, field))
        core_request.inputs.append(
            core.decode_input(
                tensor.name,
                tensor.datatype,
                list(tensor.shape),
                raw=raw,
                json_data=json_data,
                shm_region=shm_region,
                shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                shm_offset=int(params.get("shared_memory_offset", 0)),
            )
        )
    if raw_index != n_raw:
        raise InferenceServerException(
            f"raw_input_contents has {n_raw} entries but only "
            f"{raw_index} non-shared-memory inputs consumed them"
        )
    for out in request.outputs:
        params = codec.params_to_dict(out.parameters)
        core_request.outputs.append(
            CoreRequestedOutput(
                name=out.name,
                classification=int(params.get("classification", 0)),
                shm_region=params.get("shared_memory_region"),
                shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                shm_offset=int(params.get("shared_memory_offset", 0)),
            )
        )
    return core_request


def build_proto_response(core_response: CoreResponse) -> pb.ModelInferResponse:
    response = pb.ModelInferResponse(
        model_name=core_response.model_name,
        model_version=core_response.model_version,
        id=core_response.id,
    )
    codec.dict_to_params(core_response.parameters, response.parameters)
    for tensor in core_response.outputs:
        out = response.outputs.add(
            name=tensor.name,
            datatype=tensor.datatype,
            shape=tensor.shape,
        )
        if tensor.name in core_response.shm_outputs:
            region, size, offset = core_response.shm_outputs[tensor.name]
            out.parameters["shared_memory_region"].string_param = region
            out.parameters["shared_memory_byte_size"].int64_param = size
            if offset:
                out.parameters["shared_memory_offset"].int64_param = offset
            response.raw_output_contents.append(b"")
        elif tensor.datatype == "BYTES":
            response.raw_output_contents.append(
                serialize_byte_tensor(tensor.data).tobytes()
            )
        else:
            response.raw_output_contents.append(
                np.ascontiguousarray(tensor.data).tobytes()
            )
    return response


def _delegated(method_name: str):
    async def handler(self, request, context):
        await self._chaos_gate(context, method_name)
        try:
            return codec.handle_method(self.core, method_name, request)
        except codec.RpcError as e:
            await context.abort(
                _INT_TO_STATUS_CODE.get(e.status, grpc.StatusCode.UNKNOWN),
                e.message,
            )

    handler.__name__ = method_name
    return handler


class _ChaosAbort(Exception):
    """Internal marker: a drawn chaos fate must abort the stream."""


class _Servicer(GRPCInferenceServiceServicer):
    # Inference methods are registered with identity (de)serializers:
    # handlers get serialized bytes and return serialized bytes, so the
    # protobuf-free fast codec can skip proto objects on the hot path.
    raw_infer_bytes = True

    # Bounds frames buffered between the per-request executors and the
    # stream writer: a slow-reading client back-pressures the tasks
    # instead of growing server memory.
    _STREAM_QUEUE_FRAMES = 128

    def __init__(self, core: ServerCore, chaos=None):
        self.core = core
        self.chaos = chaos
        self.codec = codec.FastInferCodec(core)

    async def _chaos_gate(self, context, method: str) -> None:
        """Fault injection (ChaosPolicy): added latency plus injected
        UNAVAILABLE aborts — every drawn fate (error/reset/truncate)
        maps to an UNAVAILABLE abort, the HTTP/2 face of a dying host."""
        if self.chaos is None or not self.chaos.applies_to(method):
            return
        if self.chaos.latency_s:
            await asyncio.sleep(self.chaos.latency_s)
        fate = self.chaos.draw()
        if fate is not None:
            self.chaos.record(fate)
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, "chaos: injected unavailability"
            )

    # -- inference -----------------------------------------------------------

    def _begin_trace(self, context, core_request):
        """Trace sampling + W3C traceparent extraction from the call
        metadata (the gRPC face of the HTTP header)."""
        metadata = dict(context.invocation_metadata() or ())
        return self.core.trace_manager.begin(
            core_request.model_name,
            model_version=core_request.model_version,
            traceparent=metadata.get("traceparent"),
            request_id=core_request.id,
        )

    def _decode_infer(self, data: bytes) -> CoreRequest:
        """Serialized ModelInferRequest -> CoreRequest: protobuf-free
        fast path first, proto codec for anything it declines. Resolves
        shm-ring parameters (inputs then view the ring slot)."""
        core_request = self.codec.decode_request(data)
        if core_request is None:
            try:
                request = pb.ModelInferRequest.FromString(data)
            except Exception as e:  # noqa: BLE001 - malformed wire bytes
                raise InferenceServerException(
                    f"failed to parse ModelInferRequest: {e}"
                ) from None
            core_request = build_core_request(self.core, request)
        shm_ring.attach(self.core, core_request)
        return core_request

    def _encode_infer(self, core_request, core_response) -> bytes:
        """CoreResponse -> serialized ModelInferResponse bytes; ring
        responses divert their tensors into the slot first (part of the
        encode stage: it replaces wire serialization)."""
        if core_request.shm_ring is not None:
            core_response = core_request.shm_ring.complete(core_response)
        return self.codec.encode_response(core_response)

    async def ModelInfer(self, data, context):
        await self._chaos_gate(context, "ModelInfer")
        core = self.core
        prof = core.profiling
        # one take() covers this request's decode AND encode brackets
        measured = prof.take()
        trace = None
        core_request = None
        try:
            try:
                if measured:
                    decode_cpu0 = prof.cpu_now()
                    core_request = self._decode_infer(data)
                    prof.account(
                        "frontend_decode", prof.cpu_now() - decode_cpu0
                    )
                else:
                    core_request = self._decode_infer(data)
            except InferenceServerException:
                # rejected before reaching the engine: the statistics
                # extension never sees it, the front-end counter does
                # (same family the HTTP front-end books, protocol label
                # apart — the shared registry keeps both faces consistent)
                core.metrics.observe_frontend_error("grpc")
                raise
            # drain-aware rejection books on its own counter, after the
            # (now cheap) decode told us the model name
            core.reject_if_draining(core_request.model_name)
            trace = self._begin_trace(context, core_request)
            core_request.trace = trace
            core_response = await core.infer(core_request)
            # encode inside the try: a ring pack failure (slot too small
            # for the response) must map to a clean gRPC error, never an
            # unhandled exception after the handler "succeeded"
            if measured:
                encode_cpu0 = prof.cpu_now()
                payload = self._encode_infer(core_request, core_response)
                prof.account("encode", prof.cpu_now() - encode_cpu0)
            else:
                payload = self._encode_infer(core_request, core_response)
        except InferenceServerException as e:
            if core_request is not None and core_request.shm_ring is not None:
                core_request.shm_ring.fail()
            if trace is not None:
                trace.end(error=e.message())
            log = core.logger
            if log.verbose_hot:
                log.verbose(
                    "request",
                    model=core_request.model_name if core_request else "",
                    protocol="grpc",
                    status="error",
                    error=e.message(),
                )
            await context.abort(_status_for(e.message(), e), e.message())
        except BaseException as e:
            if core_request is not None and core_request.shm_ring is not None:
                core_request.shm_ring.fail()
            if trace is not None:
                trace.end(error=str(e))
            raise
        if trace is not None:
            trace.end()
        log = core.logger
        if log.verbose_hot:
            log.verbose(
                "request",
                model=core_request.model_name,
                protocol="grpc",
                status="ok",
                request_id=core_request.id,
            )
        return payload

    async def ModelStreamInfer(self, request_iterator, context):
        """Bidirectional inference stream.

        Requests are processed IN ORDER by default (existing decoupled
        semantics). A request carrying the ``multiplex`` parameter (the
        clients' persistent-stream mode) executes as its own task, so
        many unary infers share one stream without serializing on each
        other — responses interleave and are correlated by request id.
        """
        core = self.core
        prof = core.profiling
        out_q: "asyncio.Queue" = asyncio.Queue(self._STREAM_QUEUE_FRAMES)
        DONE = object()
        ABORT = object()
        tasks = set()

        async def emit(core_request, core_response) -> None:
            if prof.take():
                encode_cpu0 = prof.cpu_now()
                frame = self.codec.encode_stream_response(
                    core_request.shm_ring.complete(core_response)
                    if core_request.shm_ring is not None
                    else core_response
                )
                prof.account("encode", prof.cpu_now() - encode_cpu0)
            else:
                if core_request.shm_ring is not None:
                    core_response = core_request.shm_ring.complete(
                        core_response
                    )
                frame = self.codec.encode_stream_response(core_response)
            await out_q.put(frame)

        async def run_one(core_request, trace) -> None:
            try:
                core_request.trace = trace
                if core_request.shm_ring is not None:
                    # ring slots hold exactly one response; decoupled
                    # models reject ring requests via the unary path
                    await emit(core_request, await core.infer(core_request))
                else:
                    async for core_response in core.infer_decoupled(
                        core_request
                    ):
                        await emit(core_request, core_response)
            except InferenceServerException as e:
                if core_request.shm_ring is not None:
                    core_request.shm_ring.fail()
                if trace is not None:
                    trace.end(error=e.message())
                log = core.logger
                if log.verbose_hot:
                    log.verbose(
                        "request",
                        model=core_request.model_name,
                        protocol="grpc",
                        status="error",
                        error=e.message(),
                        streaming=True,
                    )
                await out_q.put(
                    self.codec.encode_stream_error(
                        e.message(), core_request.id
                    )
                )
                return
            except BaseException as e:
                if core_request.shm_ring is not None:
                    core_request.shm_ring.fail()
                if trace is not None:
                    trace.end(error=str(e) or type(e).__name__)
                raise
            if trace is not None:
                trace.end()

        async def run_task(core_request, trace) -> None:
            try:
                await run_one(core_request, trace)
            except asyncio.CancelledError:
                # stream teardown cancelled us: the writer is gone, do
                # not block on the (possibly full) frame queue
                raise
            except BaseException as e:  # noqa: BLE001 - surfaced to writer
                try:
                    out_q.put_nowait((ABORT, e))
                except asyncio.QueueFull:
                    # a live writer will drain the queue; a dead writer
                    # cancels this task out of the blocking put
                    await out_q.put((ABORT, e))

        async def reader() -> None:
            try:
                async for data in request_iterator:
                    # an injected fault aborts the whole stream with
                    # UNAVAILABLE (connection-loss semantics); the abort
                    # itself happens on the writer coroutine below
                    if self.chaos is not None and self.chaos.applies_to(
                        "ModelStreamInfer"
                    ):
                        if self.chaos.latency_s:
                            await asyncio.sleep(self.chaos.latency_s)
                        fate = self.chaos.draw()
                        if fate is not None:
                            self.chaos.record(fate)
                            await out_q.put((ABORT, _ChaosAbort()))
                            return
                    trace = None
                    core_request = None
                    try:
                        try:
                            if prof.take():
                                decode_cpu0 = prof.cpu_now()
                                core_request = self._decode_infer(data)
                                prof.account(
                                    "frontend_decode",
                                    prof.cpu_now() - decode_cpu0,
                                )
                            else:
                                core_request = self._decode_infer(data)
                        except InferenceServerException:
                            core.metrics.observe_frontend_error("grpc")
                            raise
                        # drain-aware: rejected stream requests surface
                        # as clean in-band errors, never cancelled streams
                        core.reject_if_draining(core_request.model_name)
                        trace = self._begin_trace(context, core_request)
                    except InferenceServerException as e:
                        if (
                            core_request is not None
                            and core_request.shm_ring is not None
                        ):
                            # rejection after attach: release the slot or
                            # the in-use gauge leaks
                            core_request.shm_ring.fail()
                        if trace is not None:
                            trace.end(error=e.message())
                        log = core.logger
                        if log.verbose_hot:
                            log.verbose(
                                "request",
                                protocol="grpc",
                                status="error",
                                error=e.message(),
                                streaming=True,
                            )
                        # echo the request id so multiplexed clients can
                        # correlate the failure to ITS request ("" only
                        # when the bytes never decoded)
                        await out_q.put(
                            self.codec.encode_stream_error(
                                e.message(),
                                core_request.id
                                if core_request is not None
                                else "",
                            )
                        )
                        continue
                    if core_request.parameters.pop("multiplex", False):
                        task = asyncio.ensure_future(
                            run_task(core_request, trace)
                        )
                        tasks.add(task)
                        task.add_done_callback(tasks.discard)
                    else:
                        await run_one(core_request, trace)
                if tasks:
                    await asyncio.gather(*tasks, return_exceptions=True)
            except asyncio.CancelledError:
                # writer teardown cancelled us: never block on the
                # (possibly full, no-longer-drained) frame queue
                raise
            except BaseException as e:  # noqa: BLE001 - surfaced to writer
                await out_q.put((ABORT, e))
                return
            await out_q.put(DONE)

        reader_task = asyncio.ensure_future(reader())
        try:
            while True:
                item = await out_q.get()
                if item is DONE:
                    break
                if type(item) is tuple and item[0] is ABORT:
                    error = item[1]
                    if isinstance(error, _ChaosAbort):
                        await context.abort(
                            grpc.StatusCode.UNAVAILABLE,
                            "chaos: injected unavailability",
                        )
                    raise error
                yield item
        finally:
            reader_task.cancel()
            for task in list(tasks):
                task.cancel()


# Bind every non-inference method to the shared codec implementation.
for _method in codec.METHODS:
    setattr(_Servicer, _method, _delegated(_method))


async def serve_grpc(
    core: ServerCore, host: str = "0.0.0.0", port: int = 8001, chaos=None
):
    """Start the gRPC server; returns (server, bound_port).

    ``chaos`` (a :class:`client_tpu.resilience.ChaosPolicy`) enables
    fault injection for resilience testing."""
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
            ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
        ]
    )
    add_GRPCInferenceServiceServicer_to_server(
        _Servicer(core, chaos=chaos), server
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server, bound
