"""KServe v2 gRPC front-end (grpc.aio) over :class:`ServerCore`.

Implements inference.GRPCInferenceService including decoupled
``ModelStreamInfer`` (one stream, many responses per request — the token
streaming path) and the system/TPU shared-memory registration RPCs.
"""

import asyncio
from typing import Any, Dict, List

import grpc
import numpy as np

from client_tpu.grpc._generated import grpc_service_pb2 as pb
from client_tpu.grpc._generated import model_config_pb2 as mc
from client_tpu.grpc._service_stubs import (
    GRPCInferenceServiceServicer,
    add_GRPCInferenceServiceServicer_to_server,
)
from client_tpu.server.core import (
    SERVER_EXTENSIONS,
    SERVER_NAME,
    SERVER_VERSION,
    CoreRequest,
    CoreRequestedOutput,
    CoreResponse,
    ServerCore,
)
from client_tpu.utils import (
    InferenceServerException,
    serialize_byte_tensor,
)

MAX_GRPC_MESSAGE_SIZE = 2**31 - 1  # INT32_MAX, both directions


def _status_for(message: str) -> grpc.StatusCode:
    lowered = message.lower()
    if "not found" in lowered or "unknown model" in lowered:
        return grpc.StatusCode.NOT_FOUND
    if "not ready" in lowered or "unavailable" in lowered:
        return grpc.StatusCode.UNAVAILABLE
    if "not implemented" in lowered or "no cuda" in lowered:
        return grpc.StatusCode.UNIMPLEMENTED
    return grpc.StatusCode.INVALID_ARGUMENT


def _params_to_dict(proto_params) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, p in proto_params.items():
        which = p.WhichOneof("parameter_choice")
        if which is not None:
            out[key] = getattr(p, which)
    return out


def _dict_to_params(values: Dict[str, Any], proto_params) -> None:
    for key, value in values.items():
        if isinstance(value, bool):
            proto_params[key].bool_param = value
        elif isinstance(value, int):
            proto_params[key].int64_param = value
        elif isinstance(value, float):
            proto_params[key].double_param = value
        else:
            proto_params[key].string_param = str(value)


_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


class _Servicer(GRPCInferenceServiceServicer):
    def __init__(self, core: ServerCore):
        self.core = core

    # -- health / metadata ---------------------------------------------------

    async def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=self.core.live)

    async def ServerReady(self, request, context):
        return pb.ServerReadyResponse(ready=self.core.live)

    async def ModelReady(self, request, context):
        return pb.ModelReadyResponse(
            ready=self.core.repository.is_ready(request.name, request.version)
        )

    async def ServerMetadata(self, request, context):
        return pb.ServerMetadataResponse(
            name=SERVER_NAME,
            version=SERVER_VERSION,
            extensions=SERVER_EXTENSIONS,
        )

    async def ModelMetadata(self, request, context):
        try:
            model = self.core.repository.get(request.name, request.version)
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        meta = model.metadata()
        response = pb.ModelMetadataResponse(
            name=meta["name"],
            versions=meta["versions"],
            platform=meta["platform"],
        )
        for io_key, target in (("inputs", response.inputs), ("outputs", response.outputs)):
            for tensor in meta[io_key]:
                target.add(
                    name=tensor["name"],
                    datatype=tensor["datatype"],
                    shape=tensor["shape"],
                )
        return response

    async def ModelConfig(self, request, context):
        try:
            model = self.core.repository.get(request.name, request.version)
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        cfg = model.config()
        proto = mc.ModelConfig(
            name=cfg["name"],
            platform=cfg["platform"],
            backend=cfg["backend"],
            max_batch_size=cfg["max_batch_size"],
        )
        for tensor in cfg["input"]:
            proto.input.add(
                name=tensor["name"],
                data_type=mc.DataType.Value(tensor["data_type"]),
                dims=tensor["dims"],
            )
        for tensor in cfg["output"]:
            proto.output.add(
                name=tensor["name"],
                data_type=mc.DataType.Value(tensor["data_type"]),
                dims=tensor["dims"],
            )
        proto.model_transaction_policy.decoupled = cfg[
            "model_transaction_policy"
        ]["decoupled"]
        return pb.ModelConfigResponse(config=proto)

    # -- statistics ----------------------------------------------------------

    async def ModelStatistics(self, request, context):
        try:
            stats = self.core.statistics(request.name, request.version)
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        response = pb.ModelStatisticsResponse()
        for snap in stats["model_stats"]:
            entry = response.model_stats.add(
                name=snap["name"],
                version=snap["version"],
                last_inference=snap["last_inference"],
                inference_count=snap["inference_count"],
                execution_count=snap["execution_count"],
            )
            for field, duration in snap["inference_stats"].items():
                target = getattr(entry.inference_stats, field)
                target.count = duration["count"]
                target.ns = duration["ns"]
            # Decoupled per-response statistics (response_stats map keyed
            # by response index; key "0" aggregates first responses).
            for key, fields in snap.get("response_stats", {}).items():
                rs = entry.response_stats[key]
                for field, duration in fields.items():
                    target = getattr(rs, field)
                    target.count = duration["count"]
                    target.ns = duration["ns"]
        return response

    # -- repository ----------------------------------------------------------

    async def RepositoryIndex(self, request, context):
        response = pb.RepositoryIndexResponse()
        for entry in self.core.repository.index():
            if request.ready and entry["state"] != "READY":
                continue
            response.models.add(**entry)
        return response

    async def RepositoryModelLoad(self, request, context):
        params = _params_to_dict(request.parameters)
        config = params.get("config")
        try:
            self.core.repository.load(
                request.model_name,
                config_override=config if isinstance(config, str) else None,
            )
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        return pb.RepositoryModelLoadResponse()

    async def RepositoryModelUnload(self, request, context):
        try:
            self.core.repository.unload(request.model_name)
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        return pb.RepositoryModelUnloadResponse()

    # -- shared memory -------------------------------------------------------

    async def SystemSharedMemoryStatus(self, request, context):
        response = pb.SystemSharedMemoryStatusResponse()
        for name, region in self.core.shm.status("system", request.name).items():
            response.regions[name].name = region["name"]
            response.regions[name].key = region["key"]
            response.regions[name].offset = region["offset"]
            response.regions[name].byte_size = region["byte_size"]
        return response

    async def SystemSharedMemoryRegister(self, request, context):
        try:
            self.core.shm.register_system(
                request.name, request.key, request.offset, request.byte_size
            )
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        return pb.SystemSharedMemoryRegisterResponse()

    async def SystemSharedMemoryUnregister(self, request, context):
        if request.name:
            self.core.shm.unregister(request.name, kind="system")
        else:
            self.core.shm.unregister_all(kind="system")
        return pb.SystemSharedMemoryUnregisterResponse()

    async def CudaSharedMemoryStatus(self, request, context):
        return pb.CudaSharedMemoryStatusResponse()

    async def CudaSharedMemoryRegister(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "this server has no CUDA devices; use TPU or system shared memory",
        )

    async def CudaSharedMemoryUnregister(self, request, context):
        return pb.CudaSharedMemoryUnregisterResponse()

    async def TpuSharedMemoryStatus(self, request, context):
        response = pb.TpuSharedMemoryStatusResponse()
        for name, region in self.core.shm.status("tpu", request.name).items():
            response.regions[name].name = region["name"]
            response.regions[name].device_id = region["device_id"]
            response.regions[name].byte_size = region["byte_size"]
            response.regions[name].key = region["key"]
        return response

    async def TpuSharedMemoryRegister(self, request, context):
        try:
            self.core.shm.register_tpu(
                request.name,
                request.raw_handle,
                request.device_id,
                request.byte_size,
            )
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        return pb.TpuSharedMemoryRegisterResponse()

    async def TpuSharedMemoryUnregister(self, request, context):
        if request.name:
            self.core.shm.unregister(request.name, kind="tpu")
        else:
            self.core.shm.unregister_all(kind="tpu")
        return pb.TpuSharedMemoryUnregisterResponse()

    # -- trace / log ---------------------------------------------------------

    async def TraceSetting(self, request, context):
        if request.settings:
            for key, value in request.settings.items():
                if value.value:
                    self.core.trace_settings[key] = list(value.value)
        response = pb.TraceSettingResponse()
        for key, value in self.core.trace_settings.items():
            values = value if isinstance(value, list) else [str(value)]
            response.settings[key].value.extend([str(v) for v in values])
        return response

    async def LogSettings(self, request, context):
        for key, value in request.settings.items():
            which = value.WhichOneof("parameter_choice")
            if which is not None:
                self.core.log_settings[key] = getattr(value, which)
        response = pb.LogSettingsResponse()
        for key, value in self.core.log_settings.items():
            if isinstance(value, bool):
                response.settings[key].bool_param = value
            elif isinstance(value, int):
                response.settings[key].uint32_param = value
            else:
                response.settings[key].string_param = str(value)
        return response

    # -- inference -----------------------------------------------------------

    def _build_core_request(self, request: pb.ModelInferRequest) -> CoreRequest:
        core_request = CoreRequest(
            model_name=request.model_name,
            model_version=request.model_version,
            id=request.id,
            parameters=_params_to_dict(request.parameters),
        )
        # raw_input_contents entries are consumed in order by the inputs that
        # are NOT sourced from shared memory (Triton semantics: shm inputs
        # contribute no raw entry).
        n_raw = len(request.raw_input_contents)
        raw_index = 0
        for tensor in request.inputs:
            params = _params_to_dict(tensor.parameters)
            shm_region = params.get("shared_memory_region")
            raw = None
            json_data = None
            if shm_region is not None:
                pass
            elif raw_index < n_raw:
                raw = request.raw_input_contents[raw_index]
                raw_index += 1
            elif tensor.HasField("contents"):
                field = _CONTENTS_FIELD.get(tensor.datatype)
                if field is None:
                    raise InferenceServerException(
                        f"datatype '{tensor.datatype}' has no proto contents "
                        "representation; use raw_input_contents"
                    )
                json_data = list(getattr(tensor.contents, field))
            core_request.inputs.append(
                self.core.decode_input(
                    tensor.name,
                    tensor.datatype,
                    list(tensor.shape),
                    raw=raw,
                    json_data=json_data,
                    shm_region=shm_region,
                    shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                    shm_offset=int(params.get("shared_memory_offset", 0)),
                )
            )
        if raw_index != n_raw:
            raise InferenceServerException(
                f"raw_input_contents has {n_raw} entries but only "
                f"{raw_index} non-shared-memory inputs consumed them"
            )
        for out in request.outputs:
            params = _params_to_dict(out.parameters)
            core_request.outputs.append(
                CoreRequestedOutput(
                    name=out.name,
                    classification=int(params.get("classification", 0)),
                    shm_region=params.get("shared_memory_region"),
                    shm_byte_size=int(params.get("shared_memory_byte_size", 0)),
                    shm_offset=int(params.get("shared_memory_offset", 0)),
                )
            )
        return core_request

    def _build_proto_response(
        self, core_response: CoreResponse
    ) -> pb.ModelInferResponse:
        response = pb.ModelInferResponse(
            model_name=core_response.model_name,
            model_version=core_response.model_version,
            id=core_response.id,
        )
        _dict_to_params(core_response.parameters, response.parameters)
        for tensor in core_response.outputs:
            out = response.outputs.add(
                name=tensor.name,
                datatype=tensor.datatype,
                shape=tensor.shape,
            )
            if tensor.name in core_response.shm_outputs:
                region, size, offset = core_response.shm_outputs[tensor.name]
                out.parameters["shared_memory_region"].string_param = region
                out.parameters["shared_memory_byte_size"].int64_param = size
                if offset:
                    out.parameters["shared_memory_offset"].int64_param = offset
                response.raw_output_contents.append(b"")
            elif tensor.datatype == "BYTES":
                response.raw_output_contents.append(
                    serialize_byte_tensor(tensor.data).tobytes()
                )
            else:
                response.raw_output_contents.append(
                    np.ascontiguousarray(tensor.data).tobytes()
                )
        return response

    async def ModelInfer(self, request, context):
        try:
            core_request = self._build_core_request(request)
            core_response = await self.core.infer(core_request)
        except InferenceServerException as e:
            await context.abort(_status_for(e.message()), e.message())
        return self._build_proto_response(core_response)

    async def ModelStreamInfer(self, request_iterator, context):
        async for request in request_iterator:
            try:
                core_request = self._build_core_request(request)
                async for core_response in self.core.infer_decoupled(
                    core_request
                ):
                    yield pb.ModelStreamInferResponse(
                        infer_response=self._build_proto_response(core_response)
                    )
            except InferenceServerException as e:
                error = pb.ModelStreamInferResponse(
                    error_message=e.message(),
                    infer_response=pb.ModelInferResponse(id=request.id),
                )
                yield error


async def serve_grpc(core: ServerCore, host: str = "0.0.0.0", port: int = 8001):
    """Start the gRPC server; returns (server, bound_port)."""
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
            ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
        ]
    )
    add_GRPCInferenceServiceServicer_to_server(_Servicer(core), server)
    bound = server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server, bound
