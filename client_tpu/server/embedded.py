"""In-process runner for the embedded ("local") perf backend.

The reference's triton_c_api backend dlopens libtritonserver.so and runs the
whole server in the perf_analyzer process to measure client-overhead-free
baselines (reference client_backend/triton_c_api/triton_loader.h:85-200).
This stack's server is Python, so the native analogue dlopens libpython,
imports this module, and drives a ServerCore directly — no sockets, no HTTP
parsing in the hot path beyond the KServe binary body decode.

Wire format (matches the HTTP binary protocol so the C++ side reuses
GenerateRequestBody/ParseResponseBody):
  infer(model, body, header_len) -> bytes:
      4-byte LE status (0 ok / 1 error) + 8-byte LE response-header length
      + response body (JSON header + binary section, or error JSON).
"""

import asyncio
import struct
import threading
from typing import Optional


class EmbeddedRunner:
    def __init__(self, zoo: bool = False, model_repository: str = ""):
        from client_tpu.server.core import ServerCore
        from client_tpu.server.http_server import HttpServer
        from client_tpu.server.model_repository import build_repository

        repository = build_repository(model_repository or None, zoo=zoo)
        self.core = ServerCore(repository)
        # Reuse the HTTP front-end's request/response codecs without any
        # network or aiohttp handler in the path.
        self._http = HttpServer(self.core)
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="ctpu-embedded", daemon=True
        )
        self._thread.start()

    def infer(self, model_name: str, body: bytes, header_len: int) -> bytes:
        import json

        from client_tpu.utils import InferenceServerException

        try:
            if header_len <= 0:
                header_len = len(body)
            payload = json.loads(body[:header_len].decode("utf-8"))
            binary = body[header_len:]
            core_request = self._http._build_core_request(
                model_name, "", payload, binary
            )
            future = asyncio.run_coroutine_threadsafe(
                self.core.infer(core_request), self._loop
            )
            core_response = future.result(timeout=600)
            resp = self._http._build_response(payload, core_response, "")
            resp_body = resp.body or b""
            resp_header_len = int(
                resp.headers.get(
                    "Inference-Header-Content-Length", len(resp_body)
                )
            )
            return (
                struct.pack("<IQ", 0, resp_header_len) + bytes(resp_body)
            )
        except InferenceServerException as e:
            msg = json.dumps({"error": e.message()}).encode()
            return struct.pack("<IQ", 1, len(msg)) + msg
        except Exception as e:  # noqa: BLE001 — cross the C boundary safely
            msg = json.dumps({"error": f"embedded runner: {e}"}).encode()
            return struct.pack("<IQ", 1, len(msg)) + msg

    def model_metadata_json(self, model_name: str) -> str:
        import json

        model = self.core.repository.get(model_name, "")
        return json.dumps(model.metadata())

    def model_config_json(self, model_name: str) -> str:
        import json

        model = self.core.repository.get(model_name, "")
        return json.dumps(model.config())

    def statistics_json(self, model_name: str = "") -> str:
        import json

        return json.dumps(self.core.statistics(model_name))

    def shutdown(self) -> None:
        self._loop.call_soon_threadsafe(self._loop.stop)


_runner: Optional[EmbeddedRunner] = None


def start(zoo: bool = False, model_repository: str = "") -> EmbeddedRunner:
    """Create (or return) the process-wide runner."""
    global _runner
    if _runner is None:
        _runner = EmbeddedRunner(zoo=zoo, model_repository=model_repository)
    return _runner
