"""InferRequestedOutput for the HTTP protocol.

Capability parity with reference
src/python/library/tritonclient/http/_requested_output.py.
"""

from typing import Any, Dict


class InferRequestedOutput:
    """Describes a requested output tensor.

    Parameters
    ----------
    name:
        Output tensor name.
    binary_data:
        Ask the server to return this output in the binary section of the
        response (default True; BF16 outputs require it).
    class_count:
        If > 0, request classification results with this many classes
        instead of the raw tensor.
    """

    def __init__(self, name: str, binary_data: bool = True, class_count: int = 0):
        self._name = name
        self._parameters: Dict[str, Any] = {}
        if class_count != 0:
            self._parameters["classification"] = int(class_count)
        self._binary = bool(binary_data)
        if self._binary:
            self._parameters["binary_data"] = True

    def name(self) -> str:
        return self._name

    def set_shared_memory(
        self, region_name: str, byte_size: int, offset: int = 0
    ) -> "InferRequestedOutput":
        """Direct the server to write this output into a registered region."""
        self._parameters.pop("binary_data", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = int(byte_size)
        if offset != 0:
            self._parameters["shared_memory_offset"] = int(offset)
        return self

    def unset_shared_memory(self) -> "InferRequestedOutput":
        """Clear a previous set_shared_memory so data returns inline."""
        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)
        if self._binary:
            self._parameters["binary_data"] = True
        return self

    def _get_tensor_json(self) -> Dict[str, Any]:
        tensor: Dict[str, Any] = {"name": self._name}
        if self._parameters:
            tensor["parameters"] = dict(self._parameters)
        return tensor
