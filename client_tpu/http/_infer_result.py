"""InferResult for the HTTP protocol.

Parses the KServe v2 binary response: JSON header (size given by
``Inference-Header-Content-Length``) followed by concatenated binary output
buffers in header order. Capability parity with reference
src/python/library/tritonclient/http/_infer_result.py, with BF16 decoded to
native ``ml_dtypes.bfloat16`` arrays and a ``as_jax()`` accessor.
"""

import json
from typing import Any, Dict, Optional

import numpy as np

from client_tpu.http._utils import HEADER_CONTENT_LENGTH, decompress_body
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)


class InferResult:
    """The result of an inference request."""

    def __init__(self, response_body: bytes, header_length: Optional[int]):
        if header_length is None:
            try:
                self._result: Dict[str, Any] = json.loads(
                    response_body.decode("utf-8")
                )
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise InferenceServerException(
                    f"malformed inference response: {e}"
                ) from None
            binary = b""
        else:
            header_length = int(header_length)
            try:
                self._result = json.loads(
                    response_body[:header_length].decode("utf-8")
                )
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise InferenceServerException(
                    f"malformed inference response header: {e}"
                ) from None
            binary = response_body[header_length:]

        # Map output name -> raw buffer, walking outputs in order.
        self._output_name_to_buffer: Dict[str, bytes] = {}
        offset = 0
        for output in self._result.get("outputs", []):
            params = output.get("parameters", {})
            size = params.get("binary_data_size")
            if size is not None:
                size = int(size)
                if offset + size > len(binary):
                    raise InferenceServerException(
                        f"binary section truncated for output "
                        f"'{output.get('name')}': need {size} bytes at offset "
                        f"{offset}, have {len(binary) - offset}"
                    )
                self._output_name_to_buffer[output["name"]] = binary[
                    offset : offset + size
                ]
                offset += size

    @classmethod
    def from_response(
        cls, response_body: bytes, headers: Dict[str, str]
    ) -> "InferResult":
        """Build a result from a raw HTTP response body + headers."""
        lowered = {k.lower(): v for k, v in headers.items()}
        body = decompress_body(response_body, lowered.get("content-encoding"))
        header_length = lowered.get(HEADER_CONTENT_LENGTH.lower())
        return cls(body, header_length)

    def get_response(self) -> Dict[str, Any]:
        """The deserialized JSON response header."""
        return self._result

    def get_output(self, name: str) -> Optional[Dict[str, Any]]:
        """The JSON metadata of output ``name`` (None if absent)."""
        for output in self._result.get("outputs", []):
            if output.get("name") == name:
                return output
        return None

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        """Output ``name`` as a numpy array (None if absent)."""
        output = self.get_output(name)
        if output is None:
            return None
        datatype = output["datatype"]
        shape = [int(s) for s in output.get("shape", [])]
        if name in self._output_name_to_buffer:
            buf = self._output_name_to_buffer[name]
            if datatype == "BYTES":
                return deserialize_bytes_tensor(buf).reshape(shape)
            np_dtype = triton_to_np_dtype(datatype)
            if np_dtype is None:
                raise InferenceServerException(
                    f"unknown datatype '{datatype}' for output '{name}'"
                )
            return np.frombuffer(buf, dtype=np_dtype).reshape(shape)
        if "data" in output:
            np_dtype = triton_to_np_dtype(datatype)
            if datatype == "BYTES":
                arr = np.array(
                    [
                        d.encode("utf-8") if isinstance(d, str) else d
                        for d in output["data"]
                    ],
                    dtype=np.object_,
                )
            else:
                arr = np.array(output["data"], dtype=np_dtype)
            return arr.reshape(shape)
        return None

    def as_jax(self, name: str, device=None):
        """Output ``name`` as a jax.Array placed on ``device`` (default)."""
        host = self.as_numpy(name)
        if host is None:
            return None
        import jax

        if host.dtype == np.dtype(object):
            raise InferenceServerException(
                f"BYTES output '{name}' cannot convert to a jax.Array"
            )
        return jax.device_put(host, device)
