"""InferInput for the HTTP protocol.

Capability parity with reference
src/python/library/tritonclient/http/_infer_input.py, plus a JAX-native
path: ``set_data_from_jax`` accepts a ``jax.Array`` (any dtype jax supports,
including bfloat16) and stages it to host exactly once.
"""

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    np_to_triton_dtype,
    serialize_byte_tensor,
    )


class InferInput:
    """An input tensor for an inference request."""

    def __init__(self, name: str, shape: Sequence[int], datatype: str):
        self._name = name
        self._shape = [int(s) for s in shape]
        self._datatype = datatype
        self._parameters: Dict[str, Any] = {}
        self._raw_data: Optional[bytes] = None
        self._json_data: Optional[list] = None

    def name(self) -> str:
        return self._name

    def datatype(self) -> str:
        return self._datatype

    def shape(self) -> List[int]:
        return self._shape

    def set_shape(self, shape: Sequence[int]) -> None:
        self._shape = [int(s) for s in shape]

    def set_data_from_numpy(
        self, input_tensor: np.ndarray, binary_data: bool = True
    ) -> "InferInput":
        """Attach tensor data from a numpy array.

        ``binary_data=False`` sends the tensor inside the JSON header (not
        supported for BF16, which has no JSON representation).
        """
        if not isinstance(input_tensor, np.ndarray):
            raise InferenceServerException(
                "input tensor must be a numpy array"
            )
        dtype = np_to_triton_dtype(input_tensor.dtype)
        if dtype is None:
            raise InferenceServerException(
                f"unsupported numpy dtype {input_tensor.dtype}"
            )
        if dtype != self._datatype:
            raise InferenceServerException(
                f"got unexpected datatype {dtype} from numpy array; "
                f"expected {self._datatype}"
            )
        valid_shape = list(input_tensor.shape) == self._shape
        if not valid_shape:
            raise InferenceServerException(
                f"got unexpected numpy array shape {list(input_tensor.shape)}; "
                f"expected {self._shape}"
            )

        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)

        if not binary_data:
            if self._datatype == "BF16":
                raise InferenceServerException(
                    "BF16 tensors must use binary_data=True (no JSON form)"
                )
            self._parameters.pop("binary_data_size", None)
            self._raw_data = None
            if self._datatype == "BYTES":
                flat = []
                for obj in input_tensor.flatten():
                    if isinstance(obj, (bytes, np.bytes_)):
                        flat.append(bytes(obj).decode("utf-8"))
                    else:
                        flat.append(str(obj))
            else:
                flat = input_tensor.flatten().tolist()
            self._json_data = flat
            return self

        self._json_data = None
        if self._datatype == "BYTES":
            serialized = serialize_byte_tensor(input_tensor)
            self._raw_data = serialized.tobytes()
        else:
            self._raw_data = np.ascontiguousarray(input_tensor).tobytes()
        self._parameters["binary_data_size"] = len(self._raw_data)
        return self

    def set_data_from_jax(self, jax_array) -> "InferInput":
        """Attach tensor data from a jax.Array (single device-to-host copy).

        The TPU-first twin of ``set_data_from_numpy``: bfloat16 arrays stay
        bfloat16 on the wire (datatype BF16), no float32 upcast.
        """
        host = np.asarray(jax_array)  # device -> host staging
        return self.set_data_from_numpy(host, binary_data=True)

    def set_shared_memory(
        self, region_name: str, byte_size: int, offset: int = 0
    ) -> "InferInput":
        """Source this input's data from a pre-registered shm region."""
        self._raw_data = None
        self._json_data = None
        self._parameters.pop("binary_data_size", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = int(byte_size)
        if offset != 0:
            self._parameters["shared_memory_offset"] = int(offset)
        return self

    # -- wire building -----------------------------------------------------

    def _get_binary_data(self) -> Optional[bytes]:
        return self._raw_data

    def _get_tensor_json(self, binary_chunks: Optional[list] = None) -> Dict:
        tensor: Dict[str, Any] = {
            "name": self._name,
            "shape": self._shape,
            "datatype": self._datatype,
        }
        if self._parameters:
            tensor["parameters"] = dict(self._parameters)
        if self._raw_data is not None:
            if binary_chunks is not None:
                binary_chunks.append(self._raw_data)
        elif self._json_data is not None:
            tensor["data"] = self._json_data
        return tensor
