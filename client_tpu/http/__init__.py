"""Synchronous HTTP/REST client for KServe v2 inference servers.

A thin synchronous veneer over the asyncio client in
``client_tpu.http.aio`` (one private event-loop thread per client). Method
surface parity with the reference sync HTTP client
(reference src/python/library/tritonclient/http/_client.py:102-1500),
including ``async_infer`` which returns an :class:`InferAsyncRequest`.

Unlike the reference client (gevent-based, "not thread safe",
reference http/_client.py:102-108), this client may be used from multiple
threads: calls serialize onto the private loop's connection pool.
"""

import asyncio
import concurrent.futures
from typing import List, Optional

from client_tpu._sync_runner import EventLoopRunner
from client_tpu.http import aio as _aio
from client_tpu.http._infer_input import InferInput
from client_tpu.http._infer_result import InferResult
from client_tpu.http._requested_output import InferRequestedOutput
from client_tpu.utils import InferenceServerException

__all__ = [
    "InferenceServerClient",
    "InferAsyncRequest",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
]


class InferAsyncRequest:
    """Handle to an in-flight async_infer request."""

    def __init__(
        self,
        future: concurrent.futures.Future,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        task_box: Optional[List] = None,
    ):
        self._future = future
        self._loop = loop
        # the coroutine records its own asyncio task here once it starts
        # running on the client loop, so cancel() can reach it
        self._task_box = task_box if task_box is not None else []

    def get_result(self, block: bool = True, timeout: Optional[float] = None):
        """Wait for and return the :class:`InferResult`.

        Raises
        ------
        InferenceServerException
            If the request failed, was cancelled, or ``block=False`` and
            it is still in flight.
        """
        if not block and not self._future.done():
            raise InferenceServerException("request is not yet completed")
        try:
            return self._future.result(timeout)
        except concurrent.futures.TimeoutError:
            raise InferenceServerException(
                "timeout waiting for async infer result"
            ) from None
        except (concurrent.futures.CancelledError, asyncio.CancelledError):
            raise InferenceServerException(
                "request was cancelled"
            ) from None

    def cancel(self, timeout: Optional[float] = 5.0) -> bool:
        """Cancel the in-flight request; returns whether it was cancelled.

        Cancellation is propagated to the underlying asyncio task on the
        client's loop, then this waits up to ``timeout`` for the request
        to settle and reports whether it actually ended cancelled rather
        than completing first — completion can win the race, and then
        this returns False and ``get_result()`` still yields the result.
        """
        if self._future.done():
            return False
        if (
            self._task_box
            and self._loop is not None
            and not self._loop.is_closed()
        ):
            # running on the loop: cancel the task and let the outcome
            # (cancelled vs completed-first) propagate to the future

            def _cancel_task():
                for task in self._task_box:
                    if not task.done():
                        task.cancel()

            self._loop.call_soon_threadsafe(_cancel_task)
        elif self._future.cancel():
            # never started: the pending future cancels directly
            return True
        concurrent.futures.wait([self._future], timeout=timeout)
        if self._future.cancelled():
            return True
        if not self._future.done():
            return False
        return isinstance(self._future.exception(), asyncio.CancelledError)


def _delegated(name, doc_source=None):
    """Build a sync method delegating to the aio client's coroutine."""

    def method(self, *args, **kwargs):
        return self._runner.run(getattr(self._aio_client, name)(*args, **kwargs))

    method.__name__ = name
    src = doc_source or getattr(_aio.InferenceServerClient, name, None)
    if src is not None and src.__doc__:
        method.__doc__ = src.__doc__
    return method


class InferenceServerClient:
    """Synchronous client for the KServe v2 HTTP/REST protocol."""

    def __init__(
        self,
        url=None,
        verbose: bool = False,
        concurrency: int = 16,
        connection_timeout: float = 60.0,
        network_timeout: float = 60.0,
        ssl: bool = False,
        ssl_context=None,
        retry_policy=None,
        circuit_breaker=None,
        tracer=None,
        urls=None,
        endpoint_cooldown_s: float = 1.0,
        logger=None,
        routing_policy=None,
        hedge_policy=None,
    ):
        """``url`` may be a single ``host:port``, a comma list, or an
        :class:`~client_tpu.lifecycle.EndpointPool`; ``urls=[...]`` names
        replica endpoints for health-checked failover, ``routing_policy``
        selects among them (round_robin / least_outstanding / p2c /
        consistent_hash) and ``hedge_policy`` arms tail hedging (see the
        aio client's docs — this veneer passes all of it straight
        through)."""
        self._runner = EventLoopRunner(name=f"client-tpu-http[{url}]")
        self._aio_client = _aio.InferenceServerClient(
            url,
            verbose=verbose,
            concurrency=concurrency,
            connection_timeout=connection_timeout,
            network_timeout=network_timeout,
            ssl=ssl,
            ssl_context=ssl_context,
            retry_policy=retry_policy,
            circuit_breaker=circuit_breaker,
            tracer=tracer,
            urls=urls,
            endpoint_cooldown_s=endpoint_cooldown_s,
            logger=logger,
            routing_policy=routing_policy,
            hedge_policy=hedge_policy,
        )

    # plugin registry delegates to the aio client so headers flow through it
    def register_plugin(self, plugin):
        self._aio_client.register_plugin(plugin)

    def plugin(self):
        return self._aio_client.plugin()

    def unregister_plugin(self):
        self._aio_client.unregister_plugin()

    def endpoint_snapshot(self) -> dict:
        """Live per-endpoint pool telemetry (see
        :meth:`~client_tpu.lifecycle.EndpointPool.snapshot`); sync read
        of the aio client's pool — no loop hop needed."""
        return self._aio_client.endpoint_snapshot()

    # health
    is_server_live = _delegated("is_server_live")
    is_server_ready = _delegated("is_server_ready")
    is_model_ready = _delegated("is_model_ready")
    # metadata / config
    get_server_metadata = _delegated("get_server_metadata")
    get_model_metadata = _delegated("get_model_metadata")
    get_model_config = _delegated("get_model_config")
    # repository
    get_model_repository_index = _delegated("get_model_repository_index")
    load_model = _delegated("load_model")
    unload_model = _delegated("unload_model")
    # statistics / settings
    get_inference_statistics = _delegated("get_inference_statistics")
    update_trace_settings = _delegated("update_trace_settings")
    get_trace_settings = _delegated("get_trace_settings")
    update_log_settings = _delegated("update_log_settings")
    get_log_settings = _delegated("get_log_settings")
    # shared memory
    get_system_shared_memory_status = _delegated("get_system_shared_memory_status")
    register_system_shared_memory = _delegated("register_system_shared_memory")
    unregister_system_shared_memory = _delegated("unregister_system_shared_memory")
    get_cuda_shared_memory_status = _delegated("get_cuda_shared_memory_status")
    register_cuda_shared_memory = _delegated("register_cuda_shared_memory")
    unregister_cuda_shared_memory = _delegated("unregister_cuda_shared_memory")
    get_tpu_shared_memory_status = _delegated("get_tpu_shared_memory_status")
    register_tpu_shared_memory = _delegated("register_tpu_shared_memory")
    unregister_tpu_shared_memory = _delegated("unregister_tpu_shared_memory")
    # inference
    infer = _delegated("infer")
    infer_with_body = _delegated("infer_with_body")

    generate_request_body = staticmethod(
        _aio.InferenceServerClient.generate_request_body
    )
    parse_response_body = staticmethod(
        _aio.InferenceServerClient.parse_response_body
    )

    def async_infer(self, model_name, inputs, **kwargs) -> InferAsyncRequest:
        """Issue an inference without blocking; returns a request handle.

        ``callback``, if given, is invoked as ``callback(result, error)``
        from the client's loop thread when the request completes.
        """
        callback = kwargs.pop("callback", None)
        task_box: list = []

        async def _tracked():
            # record the task so InferAsyncRequest.cancel() can reach the
            # coroutine after it has started running on the loop
            task_box.append(asyncio.current_task())
            return await self._aio_client.infer(model_name, inputs, **kwargs)

        future = self._runner.submit(_tracked())
        if callback is not None:

            def _done(f: concurrent.futures.Future):
                result, error = None, None
                try:
                    result = f.result()
                except (
                    concurrent.futures.CancelledError,
                    asyncio.CancelledError,
                ):
                    error = InferenceServerException("request was cancelled")
                except Exception as e:  # noqa: BLE001 - surface to callback
                    error = e
                callback(result, error)

            future.add_done_callback(_done)
        return InferAsyncRequest(future, loop=self._runner.loop, task_box=task_box)

    def close(self, timeout: Optional[float] = 60.0) -> None:
        """Close the connection pool and stop the loop thread."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            self._runner.run(self._aio_client.close(), timeout=timeout)
        except Exception:
            pass  # pool teardown is best-effort; the loop stops regardless
        finally:
            self._runner.close()

    def __enter__(self) -> "InferenceServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort cleanup, mirrors close()
        try:
            if self.__dict__.get("_closed", False):
                return
            self.close(timeout=5.0)
        except Exception:
            pass
