"""KServe v2 HTTP/REST binary protocol: request construction & response parse.

Wire format (KServe v2 binary tensor extension, as implemented by Triton;
reference src/python/library/tritonclient/http/_utils.py:85-156):

- request body = JSON inference header, immediately followed by the
  concatenated raw tensor buffers of every input that uses binary data;
- the ``Inference-Header-Content-Length`` HTTP header carries the JSON size;
- each binary input declares ``parameters.binary_data_size``; outputs
  requested with ``parameters.binary_data`` come back the same way.

BF16 tensors always travel binary: JSON has no sane BF16 representation
(the reference simply errors; here the builder enforces binary for BF16).
"""

import gzip
import json
import zlib
from typing import Any, Dict, List, Optional, Tuple

from client_tpu.utils import InferenceServerException

HEADER_CONTENT_LENGTH = "Inference-Header-Content-Length"


def build_query_string(query_params: Optional[Dict[str, Any]]) -> str:
    """Render query params (scalars or lists) into a ``?a=1&b=2`` suffix."""
    if not query_params:
        return ""
    from urllib.parse import quote

    parts: List[str] = []
    for key, value in query_params.items():
        if isinstance(value, (list, tuple)):
            for v in value:
                parts.append(f"{quote(str(key))}={quote(str(v))}")
        else:
            parts.append(f"{quote(str(key))}={quote(str(value))}")
    return "?" + "&".join(parts)


def model_infer_uri(model_name: str, model_version: str = "") -> str:
    from urllib.parse import quote

    name = quote(model_name)
    if model_version:
        return f"v2/models/{name}/versions/{model_version}/infer"
    return f"v2/models/{name}/infer"


def compress_body(body: bytes, algorithm: Optional[str]) -> Tuple[bytes, Optional[str]]:
    """Compress a request body; returns (body, Content-Encoding value)."""
    if algorithm is None:
        return body, None
    if algorithm == "gzip":
        return gzip.compress(body), "gzip"
    if algorithm == "deflate":
        return zlib.compress(body), "deflate"
    raise InferenceServerException(
        f"unsupported request compression algorithm '{algorithm}'"
    )


def decompress_body(body: bytes, content_encoding: Optional[str]) -> bytes:
    """Decompress a response body per its Content-Encoding header."""
    if not content_encoding:
        return body
    encoding = content_encoding.strip().lower()
    if encoding == "gzip":
        return gzip.decompress(body)
    if encoding == "deflate":
        return zlib.decompress(body)
    if encoding == "identity":
        return body
    raise InferenceServerException(
        f"unsupported response compression algorithm '{encoding}'"
    )


def get_inference_request_body(
    inputs,
    request_id: str = "",
    outputs=None,
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[Dict[str, Any]] = None,
) -> Tuple[bytes, Optional[int]]:
    """Build the request body for an inference request.

    Returns ``(body, json_size)`` where ``json_size`` is the value for the
    ``Inference-Header-Content-Length`` header, or None when the body is pure
    JSON (no binary tensor data attached).
    """
    infer_request: Dict[str, Any] = {}
    if request_id:
        infer_request["id"] = request_id

    request_parameters: Dict[str, Any] = dict(parameters) if parameters else {}
    if sequence_id != 0 and sequence_id != "":
        request_parameters["sequence_id"] = sequence_id
        request_parameters["sequence_start"] = bool(sequence_start)
        request_parameters["sequence_end"] = bool(sequence_end)
    if priority != 0:
        request_parameters["priority"] = priority
    if timeout is not None:
        request_parameters["timeout"] = timeout
    if request_parameters:
        infer_request["parameters"] = request_parameters

    binary_chunks: List[bytes] = []
    infer_request["inputs"] = [
        inp._get_tensor_json(binary_chunks) for inp in inputs
    ]
    if outputs:
        infer_request["outputs"] = [out._get_tensor_json() for out in outputs]
    else:
        # No outputs requested: ask the server to return all outputs as
        # binary data (reference http/_utils.py:131-139 semantics).
        infer_request["parameters"] = infer_request.get("parameters", {})
        infer_request["parameters"]["binary_data_output"] = True

    header = json.dumps(infer_request).encode("utf-8")
    if binary_chunks:
        return b"".join([header] + binary_chunks), len(header)
    return header, None


def retry_after_seconds(headers) -> Optional[float]:
    """Parse a ``Retry-After`` header (delta-seconds form) from a header
    mapping; returns None when absent or unparsable (HTTP-date form is
    ignored — the servers this client talks to emit seconds)."""
    if not headers:
        return None
    for key, value in headers.items():
        if key.lower() == "retry-after":
            try:
                parsed = float(value)
            except (TypeError, ValueError):
                return None
            return parsed if parsed > 0 else None
    return None


def parse_error_response(body: bytes, status: int) -> InferenceServerException:
    """Map an HTTP error response to an InferenceServerException."""
    try:
        msg = json.loads(body.decode("utf-8", errors="replace")).get("error", "")
    except Exception:
        msg = body.decode("utf-8", errors="replace")
    if not msg:
        msg = f"inference server returned HTTP status {status}"
    return InferenceServerException(msg, status=str(status))


def raise_if_error(status: int, body: bytes) -> None:
    if status != 200:
        raise parse_error_response(body, status)


def parse_json_response(status: int, body: bytes) -> Dict[str, Any]:
    raise_if_error(status, body)
    if not body:
        return {}
    try:
        return json.loads(body.decode("utf-8"))
    except json.JSONDecodeError as e:
        raise InferenceServerException(
            f"malformed JSON in server response: {e}"
        ) from None
