"""Asyncio HTTP/REST client for KServe v2 inference servers.

This is the *primary* HTTP implementation (the sync client in
``client_tpu.http`` delegates to it through a background event loop —
inverting the reference, which built sync-on-gevent first and bolted aio on;
reference src/python/library/tritonclient/http/aio/__init__.py:92-775 is the
surface model).

Method surface parity with the reference HTTP client
(reference src/python/library/tritonclient/http/_client.py:340-1177), plus
the TPU shared-memory registration trio that replaces the CUDA one.
"""

import asyncio
import json
from typing import Any, Dict, Optional, Sequence

import aiohttp

from client_tpu._client import InferenceServerClientBase
from client_tpu._request import Request
from client_tpu.http._infer_input import InferInput
from client_tpu.http._infer_result import InferResult
from client_tpu.http._requested_output import InferRequestedOutput
from client_tpu.http._utils import (
    HEADER_CONTENT_LENGTH,
    build_query_string,
    compress_body,
    get_inference_request_body,
    model_infer_uri,
    parse_json_response,
    raise_if_error,
    retry_after_seconds,
)
from client_tpu.lifecycle import (
    EndpointPool,
    failover_retry_policy,
    hedged_send_async,
    resolve_hedge_policy,
    status_is_unavailable,
)
from client_tpu.observability.trace import (
    NOOP_TRACE,
    TRACEPARENT_HEADER,
    Tracer,
    start_trace,
)
from client_tpu.resilience import (
    CONNECTION_ERROR_STATUS,
    CircuitBreaker,
    RetryPolicy,
    run_with_resilience_async,
    sequence_is_idempotent,
)
from client_tpu.utils import InferenceServerException

__all__ = ["InferenceServerClient", "InferInput", "InferRequestedOutput", "InferResult"]


class InferenceServerClient(InferenceServerClientBase):
    """An asyncio client for the KServe v2 HTTP/REST protocol.

    Parameters
    ----------
    url:
        Host:port of the server, e.g. ``"localhost:8000"``. May also be
        a comma-separated list of endpoints or an
        :class:`~client_tpu.lifecycle.EndpointPool` (see ``urls``).
    urls:
        Optional list of equivalent endpoints (replicas behind no load
        balancer). Requests target a sticky primary; endpoints that
        return 503 / connection errors (draining or dead servers) are
        benched for ``endpoint_cooldown_s`` (or their ``Retry-After``
        hint) and traffic fails over to the next healthy endpoint —
        immediately, skipping the retry backoff. Recovering endpoints
        must pass a ``/v2/health/ready`` probe before carrying real
        traffic again. With more than one endpoint and no explicit
        ``retry_policy``, a small failover retry policy is installed so
        idempotent requests actually reroute instead of failing.
    verbose:
        Print request/response traffic.
    concurrency:
        Connection-pool size (the reference's greenlet concurrency knob).
    connection_timeout / network_timeout:
        Connect / total-read timeouts in seconds.
    ssl:
        Use https. ``ssl_context`` may carry a preconfigured
        ``ssl.SSLContext``.
    retry_policy:
        Optional :class:`client_tpu.resilience.RetryPolicy`. When set,
        idempotent requests that fail with connect errors or retryable
        HTTP statuses (429/502/503/504) are retried with capped
        exponential backoff; sequence inference is never auto-retried.
        Off by default (single attempt, as before).
    circuit_breaker:
        Optional :class:`client_tpu.resilience.CircuitBreaker` shared
        per client (or across clients): when open, requests fail fast
        with ``CircuitBreakerOpenError`` instead of piling up backoff.
    tracer:
        Optional :class:`client_tpu.observability.Tracer`. When set,
        each ``infer``/``infer_with_body`` call records client spans
        (serialize, per-attempt send/wait, deserialize) and propagates a
        W3C ``traceparent`` header the server front-ends extract. Off by
        default (no spans, no header).
    routing_policy:
        None (sticky primary) or ``round_robin`` / ``least_outstanding``
        / ``p2c`` / ``consistent_hash`` (affinity on the ``routing_key``
        request parameter) — selection over the pool's live
        per-endpoint outstanding/EWMA signals.
    hedge_policy:
        Arms request hedging for idempotent requests: seconds (fixed
        trigger), ``"p95"`` (latency-derived), or a
        :class:`~client_tpu.lifecycle.HedgePolicy`. First response wins;
        the losing attempt is cancelled and never double-counted in
        pool telemetry or retries. Requests referencing shared-memory
        regions or shm-ring tickets never hedge.
    """

    def __init__(
        self,
        url=None,
        verbose: bool = False,
        concurrency: int = 16,
        connection_timeout: float = 60.0,
        network_timeout: float = 60.0,
        ssl: bool = False,
        ssl_context=None,
        retry_policy: Optional[RetryPolicy] = None,
        circuit_breaker: Optional[CircuitBreaker] = None,
        tracer: Optional[Tracer] = None,
        urls=None,
        endpoint_cooldown_s: float = 1.0,
        logger=None,
        routing_policy=None,
        hedge_policy=None,
    ):
        super().__init__()
        scheme = "https" if ssl else "http"
        self._pool = EndpointPool.resolve(
            url,
            urls,
            cooldown_s=endpoint_cooldown_s,
            logger=logger,
            routing_policy=routing_policy,
        )
        self._hedge = resolve_hedge_policy(hedge_policy)
        for endpoint_url in self._pool.urls:
            if "://" in endpoint_url:
                raise InferenceServerException(
                    f"url should not include the scheme: '{endpoint_url}'"
                )
        self._scheme = scheme
        if self._pool.size > 1 and retry_policy is None:
            # Failover needs attempts to spend: give multi-endpoint
            # clients a small retry budget (the backoff is skipped
            # entirely when another endpoint is available).
            retry_policy = failover_retry_policy(self._pool.size)
        self._verbose = verbose
        self._ssl_context = ssl_context
        self._timeout = aiohttp.ClientTimeout(
            connect=connection_timeout, total=network_timeout
        )
        self._connector_limit = concurrency
        self._session: Optional[aiohttp.ClientSession] = None
        self._retry_policy = retry_policy
        self._circuit_breaker = circuit_breaker
        self._tracer = tracer

    # -- session lifecycle -------------------------------------------------

    def _ensure_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            connector = aiohttp.TCPConnector(
                limit=self._connector_limit, ssl=self._ssl_context
            )
            # auto_decompress off: compression is negotiated and handled by
            # this client itself (response_compression_algorithm), so the
            # Content-Encoding header always matches the body we parse.
            self._session = aiohttp.ClientSession(
                connector=connector,
                timeout=self._timeout,
                auto_decompress=False,
                headers={"Accept-Encoding": "identity"},
            )
        return self._session

    async def close(self) -> None:
        """Close the underlying connection pool."""
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def endpoint_snapshot(self) -> dict:
        """Live per-endpoint telemetry (outstanding requests, EWMA
        latency, error/reroute counters) — every request this client
        sends is bracketed through its :class:`~client_tpu.lifecycle.
        EndpointPool`; see :meth:`EndpointPool.snapshot`."""
        return self._pool.snapshot()

    async def __aenter__(self) -> "InferenceServerClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- low-level request helpers ----------------------------------------

    def _prepare_headers(
        self, headers: Optional[Dict[str, str]]
    ) -> Dict[str, str]:
        request = Request(headers or {})
        self._call_plugin(request)
        return request.headers

    async def _request_once(
        self, method, url, data, headers, timeout, trace=NOOP_TRACE
    ) -> tuple:
        """One attempt; transport failures surface as
        InferenceServerException (URL and cause in the message) rather
        than raw aiohttp/asyncio errors. With an active ``trace`` the
        attempt records a "send" span (until response headers arrive)
        and a "wait" span (body read)."""
        session = self._ensure_session()
        # only override the session's default ClientTimeout when this
        # attempt carries an explicit budget: an explicit timeout=None
        # would DISABLE the configured connection/network timeouts
        kwargs = (
            {"timeout": aiohttp.ClientTimeout(total=timeout)}
            if timeout
            else {}
        )
        span = trace.begin_span("send", attempt=trace.attempt_index())
        try:
            async with session.request(
                method, url, data=data, headers=headers, **kwargs
            ) as resp:
                trace.end_span(span)
                span = trace.begin_span("wait")
                rbody = await resp.read()
                trace.end_span(span)
                span = None
                return resp.status, rbody, dict(resp.headers)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            trace.end_span(span, error=f"{type(e).__name__}: {e}")
            raise InferenceServerException(
                f"{method} {url} failed: {type(e).__name__}: {e}",
                status=CONNECTION_ERROR_STATUS,
            ) from e

    def _endpoint_base(self, endpoint) -> str:
        return f"{self._scheme}://{endpoint.url}"

    async def _probe_endpoint(self, endpoint, timeout: float = 1.0) -> bool:
        """One /v2/health/ready probe against a specific endpoint (used
        before trusting a recovering pool member with real traffic)."""
        try:
            status, _, _ = await self._request_once(
                "GET",
                f"{self._endpoint_base(endpoint)}/v2/health/ready",
                None,
                {},
                timeout,
            )
        except InferenceServerException:
            return False
        return status == 200

    async def _pick_endpoint(
        self,
        budget_s: Optional[float] = None,
        exclude=None,
        key=None,
    ):
        """The pool's choice for the next attempt; endpoints coming back
        from a down period must pass a readiness probe first (a draining
        server answers its health endpoint long before it serves).
        Probes are budgeted against ``budget_s`` (the remaining attempt
        timeout) so they can never blow the caller's deadline.
        ``exclude`` asks for an endpoint other than the one given (the
        hedge path); ``key`` is the consistent-hash routing key."""
        pool = self._pool
        probe_timeout = 1.0
        if budget_s:
            probe_timeout = min(1.0, max(0.05, budget_s / pool.size))
        for _ in range(pool.size):
            endpoint = pool.pick(key=key, exclude=exclude)
            if not pool.needs_probe(endpoint):
                return endpoint
            if await self._probe_endpoint(endpoint, timeout=probe_timeout):
                pool.mark_up(endpoint)
                return endpoint
            pool.mark_down(endpoint)
        return pool.pick(key=key, exclude=exclude)

    @staticmethod
    def _result_ok(result) -> bool:
        return str(result[0]).startswith("2")

    async def _execute(
        self,
        method,
        path,
        data,
        headers,
        query_params,
        timeout=None,
        idempotent=True,
        probe=False,
        trace=NOOP_TRACE,
        routing_key=None,
        hedgeable=True,
    ) -> tuple:
        suffix = f"/{path}{build_query_string(query_params)}"
        prepared_headers = self._prepare_headers(headers)
        if probe:
            # liveness/readiness probes report CURRENT state: retrying
            # one would invert its purpose, and its failures while a
            # server restarts must not poison a shared circuit breaker
            url = self._endpoint_base(self._pool.pick()) + suffix
            return await self._request_once(
                method, url, data, prepared_headers, timeout
            )
        pool = self._pool
        hedge = self._hedge if (hedgeable and idempotent) else None

        async def _raw(endpoint, attempt_timeout, attempt_trace):
            # one attempt against a SPECIFIC endpoint; the pool
            # begin/finish bracket belongs to the caller
            url = self._endpoint_base(endpoint) + suffix
            if self._verbose:
                size = f" ({len(data)} bytes)" if data else ""
                print(f"{method} {url}{size}")
            try:
                result = await self._request_once(
                    method, url, data, prepared_headers, attempt_timeout,
                    trace=attempt_trace,
                )
            except InferenceServerException as e:
                if e.status() == CONNECTION_ERROR_STATUS:
                    # dead endpoint: bench it; with an alternative
                    # available the retry loop skips the backoff sleep
                    pool.observe(endpoint, token=CONNECTION_ERROR_STATUS)
                    if pool.has_alternative(endpoint):
                        e.retry_backoff_cap_s = 0.0
                raise
            token = str(result[0])
            if status_is_unavailable(token):
                # draining server: bench it for its own Retry-After hint
                pool.observe(
                    endpoint,
                    token=token,
                    retry_after_s=retry_after_seconds(result[2]),
                )
            else:
                pool.observe(endpoint, ok=True)
            return result

        if hedge is not None:

            async def _attempt(attempt_timeout):
                # two racing attempts would interleave send/wait spans on
                # one trace; the hedged pair records none (wrap_attempt
                # still records the enclosing "request" span)
                return await hedged_send_async(
                    pool,
                    hedge,
                    lambda budget, exclude: self._pick_endpoint(
                        budget, exclude=exclude, key=routing_key
                    ),
                    lambda endpoint, attempt_timeout: _raw(
                        endpoint, attempt_timeout, NOOP_TRACE
                    ),
                    attempt_timeout,
                    value_ok=self._result_ok,
                    value_token=lambda result: str(result[0]),
                )

        else:

            async def _attempt(attempt_timeout):
                endpoint = await self._pick_endpoint(
                    attempt_timeout, key=routing_key
                )
                started = pool.begin(endpoint)
                try:
                    result = await _raw(endpoint, attempt_timeout, trace)
                except asyncio.CancelledError:
                    # cancellation says nothing about the endpoint: close
                    # the bracket without booking an error
                    pool.finish(endpoint, started, ok=False, cancelled=True)
                    raise
                except InferenceServerException as e:
                    pool.finish(
                        endpoint, started, ok=False, token=e.status()
                    )
                    raise
                except BaseException:
                    # an unwrapped failure: close the bracket so the
                    # outstanding gauge never leaks
                    pool.finish(endpoint, started, ok=False)
                    raise
                ok = self._result_ok(result)
                pool.finish(
                    endpoint,
                    started,
                    ok=ok,
                    # a 4xx is an error but proves the endpoint healthy:
                    # the token keeps it out of consecutive-error ejection
                    token=None if ok else str(result[0]),
                )
                return result

        status, rbody, rheaders = await run_with_resilience_async(
            _attempt,
            retry_policy=self._retry_policy,
            circuit_breaker=self._circuit_breaker,
            budget_s=timeout or None,
            idempotent=idempotent,
            result_status=lambda value: str(value[0]),
            description=f"{method} {suffix.lstrip('/')}",
            # a 429 shed response's Retry-After is the server's own
            # backoff estimate — honored as the retry floor
            result_backoff_hint=lambda value: retry_after_seconds(value[2]),
            # ...unless the failure is endpoint-scoped (503/UNAVAILABLE)
            # and the pool has somewhere else to go: fail over NOW
            result_backoff_cap=lambda value: (
                0.0
                if status_is_unavailable(str(value[0]))
                and pool.has_alternative(None)
                else None
            ),
        )
        if self._verbose:
            print(f"-> {status} ({len(rbody)} bytes)")
        return status, rbody, rheaders

    async def _get(self, path, headers, query_params, probe=False) -> tuple:
        return await self._execute(
            "GET", path, None, headers, query_params, probe=probe
        )

    async def _post(
        self, path, body: bytes, headers, query_params, timeout=None,
        idempotent=True, trace=NOOP_TRACE, routing_key=None, hedgeable=True,
    ) -> tuple:
        return await self._execute(
            "POST",
            path,
            body,
            headers,
            query_params,
            timeout=timeout,
            idempotent=idempotent,
            trace=trace,
            routing_key=routing_key,
            hedgeable=hedgeable,
        )

    async def _get_json(self, path, headers, query_params) -> Dict[str, Any]:
        status, body, _ = await self._get(path, headers, query_params)
        return parse_json_response(status, body)

    async def _post_json(
        self,
        path,
        request: Optional[Dict[str, Any]],
        headers,
        query_params,
        idempotent: bool = True,
    ) -> Dict[str, Any]:
        body = json.dumps(request).encode("utf-8") if request is not None else b""
        status, rbody, _ = await self._post(
            path, body, headers, query_params, idempotent=idempotent
        )
        return parse_json_response(status, rbody)

    # -- health ------------------------------------------------------------

    async def is_server_live(self, headers=None, query_params=None) -> bool:
        status, _, _ = await self._get(
            "v2/health/live", headers, query_params, probe=True
        )
        return status == 200

    async def is_server_ready(self, headers=None, query_params=None) -> bool:
        status, _, _ = await self._get(
            "v2/health/ready", headers, query_params, probe=True
        )
        return status == 200

    async def is_model_ready(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> bool:
        path = f"v2/models/{model_name}"
        if model_version:
            path += f"/versions/{model_version}"
        status, _, _ = await self._get(
            f"{path}/ready", headers, query_params, probe=True
        )
        return status == 200

    # -- metadata / config -------------------------------------------------

    async def get_server_metadata(self, headers=None, query_params=None):
        return await self._get_json("v2", headers, query_params)

    async def get_model_metadata(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        path = f"v2/models/{model_name}"
        if model_version:
            path += f"/versions/{model_version}"
        return await self._get_json(path, headers, query_params)

    async def get_model_config(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        path = f"v2/models/{model_name}"
        if model_version:
            path += f"/versions/{model_version}"
        return await self._get_json(f"{path}/config", headers, query_params)

    # -- repository control ------------------------------------------------

    async def get_model_repository_index(self, headers=None, query_params=None):
        return await self._post_json(
            "v2/repository/index", None, headers, query_params
        )

    async def load_model(
        self,
        model_name,
        headers=None,
        query_params=None,
        config: Optional[str] = None,
        files: Optional[Dict[str, bytes]] = None,
    ) -> None:
        """Load (or reload) a model, optionally overriding config/files.

        ``config`` is a JSON model-config string; ``files`` maps
        ``file:<relative-path>`` names to raw content (base64'd on the wire),
        matching the reference contract
        (reference src/python/library/tritonclient/http/_client.py:620-672).
        """
        load_request: Dict[str, Any] = {}
        if config is not None or files:
            params: Dict[str, Any] = {}
            if config is not None:
                params["config"] = config
            if files:
                import base64

                for name, content in files.items():
                    params[name] = base64.b64encode(content).decode("ascii")
            load_request["parameters"] = params
        await self._post_json(
            f"v2/repository/models/{model_name}/load",
            load_request,
            headers,
            query_params,
            idempotent=False,
        )

    async def unload_model(
        self,
        model_name,
        headers=None,
        query_params=None,
        unload_dependents: bool = False,
    ) -> None:
        request = {
            "parameters": {"unload_dependents": unload_dependents}
        }
        await self._post_json(
            f"v2/repository/models/{model_name}/unload",
            request,
            headers,
            query_params,
            idempotent=False,
        )

    # -- statistics / settings ----------------------------------------------

    async def get_inference_statistics(
        self, model_name="", model_version="", headers=None, query_params=None
    ):
        if model_name:
            path = f"v2/models/{model_name}"
            if model_version:
                path += f"/versions/{model_version}"
            path += "/stats"
        else:
            path = "v2/models/stats"
        return await self._get_json(path, headers, query_params)

    async def update_trace_settings(
        self, model_name=None, settings=None, headers=None, query_params=None
    ):
        path = (
            f"v2/models/{model_name}/trace/setting"
            if model_name
            else "v2/trace/setting"
        )
        return await self._post_json(
            path, settings or {}, headers, query_params
        )

    async def get_trace_settings(
        self, model_name=None, headers=None, query_params=None
    ):
        path = (
            f"v2/models/{model_name}/trace/setting"
            if model_name
            else "v2/trace/setting"
        )
        return await self._get_json(path, headers, query_params)

    async def update_log_settings(
        self, settings, headers=None, query_params=None
    ):
        return await self._post_json("v2/logging", settings, headers, query_params)

    async def get_log_settings(self, headers=None, query_params=None):
        return await self._get_json("v2/logging", headers, query_params)

    # -- shared memory ------------------------------------------------------

    async def get_system_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        path = "v2/systemsharedmemory"
        if region_name:
            path += f"/region/{region_name}"
        return await self._get_json(f"{path}/status", headers, query_params)

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, query_params=None
    ) -> None:
        request = {"key": key, "offset": offset, "byte_size": byte_size}
        await self._post_json(
            f"v2/systemsharedmemory/region/{name}/register",
            request,
            headers,
            query_params,
            idempotent=False,
        )

    async def unregister_system_shared_memory(
        self, name="", headers=None, query_params=None
    ) -> None:
        path = "v2/systemsharedmemory"
        if name:
            path += f"/region/{name}"
        await self._post_json(
            f"{path}/unregister", None, headers, query_params,
            idempotent=False,
        )

    async def get_cuda_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        path = "v2/cudasharedmemory"
        if region_name:
            path += f"/region/{region_name}"
        return await self._get_json(f"{path}/status", headers, query_params)

    async def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, query_params=None
    ) -> None:
        """Register a CUDA-IPC region (only meaningful against GPU servers)."""
        import base64

        request = {
            "raw_handle": {
                "b64": base64.b64encode(raw_handle).decode("ascii")
            },
            "device_id": device_id,
            "byte_size": byte_size,
        }
        await self._post_json(
            f"v2/cudasharedmemory/region/{name}/register",
            request,
            headers,
            query_params,
            idempotent=False,
        )

    async def unregister_cuda_shared_memory(
        self, name="", headers=None, query_params=None
    ) -> None:
        path = "v2/cudasharedmemory"
        if name:
            path += f"/region/{name}"
        await self._post_json(
            f"{path}/unregister", None, headers, query_params,
            idempotent=False,
        )

    async def get_tpu_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        path = "v2/tpusharedmemory"
        if region_name:
            path += f"/region/{region_name}"
        return await self._get_json(f"{path}/status", headers, query_params)

    async def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, query_params=None
    ) -> None:
        """Register a TPU shared-memory region (client_tpu extension).

        ``raw_handle`` comes from
        :func:`client_tpu.utils.tpu_shared_memory.get_raw_handle`.
        """
        import base64

        request = {
            "raw_handle": {
                "b64": base64.b64encode(raw_handle).decode("ascii")
            },
            "device_id": device_id,
            "byte_size": byte_size,
        }
        await self._post_json(
            f"v2/tpusharedmemory/region/{name}/register",
            request,
            headers,
            query_params,
            idempotent=False,
        )

    async def unregister_tpu_shared_memory(
        self, name="", headers=None, query_params=None
    ) -> None:
        path = "v2/tpusharedmemory"
        if name:
            path += f"/region/{name}"
        await self._post_json(
            f"{path}/unregister", None, headers, query_params,
            idempotent=False,
        )

    # -- inference ----------------------------------------------------------

    @staticmethod
    def generate_request_body(
        inputs,
        request_id="",
        outputs=None,
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        parameters=None,
    ):
        """Build an inference request body offline.

        Returns ``(body, json_size)`` — json_size is None for pure-JSON
        bodies (reference http/_client.py:1219-1300 static twin).
        """
        return get_inference_request_body(
            inputs,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )

    @staticmethod
    def parse_response_body(response_body, header_length=None):
        """Parse a raw response body built by :meth:`generate_request_body`'s
        round trip (reference http/_client.py:1304-1330 static twin)."""
        return InferResult(response_body, header_length)

    async def infer_with_body(
        self,
        model_name: str,
        body: bytes,
        json_size: Optional[int],
        model_version: str = "",
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
        client_timeout: Optional[float] = None,
        idempotent: bool = True,
        routing_key=None,
    ) -> InferResult:
        """Send a body built by :meth:`generate_request_body` (reusable —
        deterministic request bodies can be built once and resent; the
        reference's static GenerateRequestBody serves the same offline
        role, reference http_client.cc:1286-1351).

        Pass ``idempotent=False`` when the prepared body carries sequence
        state so a configured retry policy never auto-retries it; as a
        safety net, bodies whose JSON header names a ``sequence_id`` are
        detected and demoted to non-idempotent automatically. The same
        header scan keeps shared-memory bodies out of request hedging
        (single-writer buffers must not race a duplicate).
        ``routing_key`` feeds consistent-hash affinity (prepared bodies
        are opaque here, so the key is the caller's to supply)."""
        if idempotent and self._retry_policy is not None:
            header = body[:json_size] if json_size is not None else body
            if b'"sequence_id"' in header:
                idempotent = False
        hedgeable = True
        if self._hedge is not None:
            header = body[:json_size] if json_size is not None else body
            hedgeable = (
                b"shared_memory_region" not in header
                and b"shm_ring_region" not in header
            )
        extra_headers = dict(headers) if headers else {}
        if json_size is not None:
            extra_headers[HEADER_CONTENT_LENGTH] = str(json_size)
        trace = start_trace(
            self._tracer, "infer", surface="http", model=model_name
        )
        if trace.traceparent:
            extra_headers[TRACEPARENT_HEADER] = trace.traceparent
        try:
            status, rbody, rheaders = await self._post(
                model_infer_uri(model_name, model_version),
                body,
                extra_headers,
                query_params,
                timeout=client_timeout,
                idempotent=idempotent,
                trace=trace,
                routing_key=routing_key,
                hedgeable=hedgeable,
            )
            with trace.stage("deserialize"):
                raise_if_error(status, rbody)
                result = InferResult.from_response(rbody, rheaders)
        except BaseException as e:
            trace.finish(error=e)
            raise
        trace.finish()
        return result

    async def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
        request_compression_algorithm: Optional[str] = None,
        response_compression_algorithm: Optional[str] = None,
        parameters: Optional[Dict[str, Any]] = None,
    ) -> InferResult:
        """Run a synchronous (from the caller's view: awaited) inference.

        ``priority`` and ``timeout`` match the gRPC client surface
        (``client_tpu.grpc.InferenceServerClient.infer``): both travel as
        KServe request *parameters* — ``priority`` picks the server-side
        scheduler queue level (1 = highest) and ``timeout`` is the queue
        timeout in MICROSECONDS the server may enforce before execution.
        ``client_timeout`` (seconds) is this client's own transport
        budget across attempts — the two deadlines are independent."""
        if timeout is not None and not isinstance(timeout, int):
            # fail LOUDLY: this kwarg used to be a seconds-float transport
            # budget; a silently truncated float would reach the server as
            # a microsecond queue deadline and shed every request
            raise InferenceServerException(
                "infer(timeout=...) is the server queue timeout in "
                "MICROSECONDS (int), matching the gRPC client; use "
                "client_timeout= (seconds) for the transport budget"
            )
        trace = start_trace(
            self._tracer, "infer", surface="http", model=model_name
        )
        try:
            with trace.stage("serialize"):
                body, json_size = get_inference_request_body(
                    inputs,
                    request_id=request_id,
                    outputs=outputs,
                    sequence_id=sequence_id,
                    sequence_start=sequence_start,
                    sequence_end=sequence_end,
                    priority=priority,
                    timeout=int(timeout) if timeout else None,
                    parameters=parameters,
                )
                extra_headers = dict(headers) if headers else {}
                body, encoding = compress_body(
                    body, request_compression_algorithm
                )
                if encoding:
                    extra_headers["Content-Encoding"] = encoding
                if response_compression_algorithm:
                    extra_headers["Accept-Encoding"] = (
                        response_compression_algorithm
                    )
                if json_size is not None:
                    extra_headers[HEADER_CONTENT_LENGTH] = str(json_size)
            if trace.traceparent:
                extra_headers[TRACEPARENT_HEADER] = trace.traceparent

            routing_key = None
            key_param = self._pool.key_parameter
            if key_param is not None and parameters:
                routing_key = parameters.get(key_param)
            hedgeable = True
            if self._hedge is not None:
                # shm-ring tickets (and any shared-memory region ref) are
                # single-writer buffers: a hedged duplicate would race
                hedgeable = not (
                    (parameters and "shm_ring_region" in parameters)
                    or any(
                        inp._parameters.get("shared_memory_region")
                        for inp in inputs
                    )
                    or any(
                        out._parameters.get("shared_memory_region")
                        for out in (outputs or ())
                    )
                )
            status, rbody, rheaders = await self._post(
                model_infer_uri(model_name, model_version),
                body,
                extra_headers,
                query_params,
                timeout=client_timeout,
                idempotent=sequence_is_idempotent(sequence_id),
                trace=trace,
                routing_key=routing_key,
                hedgeable=hedgeable,
            )
            with trace.stage("deserialize"):
                raise_if_error(status, rbody)
                result = InferResult.from_response(rbody, rheaders)
        except BaseException as e:
            trace.finish(error=e)
            raise
        trace.finish()
        return result
