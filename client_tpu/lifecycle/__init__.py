"""Graceful lifecycle: drain-aware shutdown and client endpoint failover.

The robustness layer for the boring disasters — deploys, model reloads,
instance restarts — so a rolling restart under load drops ~zero requests:

Server side
-----------
:class:`DrainController`
    Explicit SERVING -> DRAINING -> STOPPED states with an in-flight
    census over all four ServerCore execution paths. Draining flips
    readiness false (liveness stays true, so load balancers drain),
    rejects new inferences with 503 + ``Retry-After`` / gRPC
    ``UNAVAILABLE``, and lets in-flight and queued work finish up to a
    drain deadline before anything is cancelled.
:class:`ServerDrainingError`
    The clean rejection both front-ends map without message parsing.

Client side
-----------
:class:`EndpointPool`
    Accepted everywhere a ``url`` is today (``urls=[...]`` or an explicit
    pool): sticky-primary routing that health-checks recovering endpoints
    via ``/v2/health/ready`` (gRPC ``ServerReady``), benches draining or
    dead endpoints, integrates per-endpoint
    :class:`~client_tpu.resilience.CircuitBreaker` instances, and fails
    over mid-retry-loop — immediately, skipping the backoff sleep — when
    another endpoint is available.

Everything here is clock-injectable (enforced by ``tools/clock_lint.py``)
so the lifecycle test suite runs on fake clocks.
"""

from client_tpu.lifecycle.drain import (
    DRAINING,
    RECOVERING,
    SERVING,
    STATE_VALUES,
    STOPPED,
    DrainController,
    ServerDrainingError,
)
from client_tpu.lifecycle.hedge import (
    HedgePolicy,
    hedged_send_async,
    resolve_hedge_policy,
)
from client_tpu.lifecycle.pool import (
    UNAVAILABLE_TOKENS,
    Endpoint,
    EndpointPool,
    failover_retry_policy,
    grpc_status_is_endpoint_outage,
    status_is_unavailable,
)
from client_tpu.lifecycle.routing import (
    ROUTING_POLICY_NAMES,
    ConsistentHashPolicy,
    LeastOutstandingPolicy,
    PowerOfTwoPolicy,
    RoundRobinPolicy,
    RoutingPolicy,
    resolve_routing_policy,
)

__all__ = [
    "DRAINING",
    "RECOVERING",
    "ROUTING_POLICY_NAMES",
    "SERVING",
    "STATE_VALUES",
    "STOPPED",
    "UNAVAILABLE_TOKENS",
    "ConsistentHashPolicy",
    "DrainController",
    "Endpoint",
    "EndpointPool",
    "HedgePolicy",
    "LeastOutstandingPolicy",
    "PowerOfTwoPolicy",
    "RoundRobinPolicy",
    "RoutingPolicy",
    "ServerDrainingError",
    "failover_retry_policy",
    "grpc_status_is_endpoint_outage",
    "hedged_send_async",
    "resolve_hedge_policy",
    "resolve_routing_policy",
    "status_is_unavailable",
]
