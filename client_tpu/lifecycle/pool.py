"""Client-side endpoint pool: health-aware routing + failover state.

Every client surface accepts an :class:`EndpointPool` (or ``urls=[...]``)
wherever a single ``url`` is accepted today. The pool is pure state — it
owns no sockets and issues no probes itself (the owning client probes
``/v2/health/ready`` / gRPC ``ServerReady`` when the pool says a
recovering endpoint :meth:`needs_probe`), so one implementation serves
all four surfaces and tests drive it with a fake clock
(``tools/clock_lint.py`` covers this package).

Routing defaults to sticky-primary with failover: :meth:`pick` returns
the current primary until a request against it fails with an
unavailability signal (connect error, HTTP 503, gRPC UNAVAILABLE — a
draining or dead server), at which point the endpoint is marked down for
``cooldown_s`` (or the server's own ``Retry-After`` hint) and the primary
advances. A :class:`~client_tpu.lifecycle.routing.RoutingPolicy`
(``routing_policy=``) replaces the sticky scan with load-aware selection
— round-robin, least-outstanding, power-of-two-choices on the live
outstanding/EWMA signals, or consistent-hash affinity on a request key.
Per-endpoint :class:`~client_tpu.resilience.CircuitBreaker` instances
(optional) are consulted by :meth:`pick` and fed by :meth:`observe`, so a
flapping endpoint fails fast instead of eating a timeout per attempt.

On top of the reactive down/cooldown machine the pool runs **outlier
ejection**: an endpoint that fails ``eject_consecutive_errors`` attempts
in a row, or whose EWMA latency drifts past ``eject_ewma_factor`` x the
median of its peers, is ejected for ``ejection_cooldown_s`` and must pass
the same readiness re-probe a benched endpoint does before carrying
traffic again. Ejection never removes the last healthy endpoint.
"""

import threading
import time
from typing import Callable, List, Optional, Sequence, Union

from client_tpu.lifecycle.routing import resolve_routing_policy
from client_tpu.resilience import CONNECTION_ERROR_STATUS

# Status tokens that mean "this endpoint cannot serve right now" — route
# around it. 503 / UNAVAILABLE are what a draining server returns; a
# connection error is what a dead one produces.
UNAVAILABLE_TOKENS = frozenset({"503", "UNAVAILABLE", CONNECTION_ERROR_STATUS})


def status_is_unavailable(token: Optional[str]) -> bool:
    """True when a status token ("503", "StatusCode.UNAVAILABLE",
    "CONNECTION_ERROR") signals an endpoint-level outage."""
    if not token:
        return False
    return token.rsplit(".", 1)[-1] in UNAVAILABLE_TOKENS


def grpc_status_is_endpoint_outage(token: Optional[str]) -> bool:
    """The unary-gRPC superset of :func:`status_is_unavailable`: a wire
    ``CANCELLED`` on a unary call means the SERVER cancelled an accepted
    RPC — the shutdown race a draining replica can lose (observed: the
    grpc.aio front-end's stop(grace) window). A locally-cancelled call
    never produces this token (asyncio raises ``CancelledError``, the
    sync future raises ``FutureCancelledError`` — neither is an
    RpcError), so on the unary paths CANCELLED is an endpoint-level
    outage signal, routed around like UNAVAILABLE."""
    if status_is_unavailable(token):
        return True
    return bool(token) and token.rsplit(".", 1)[-1] == "CANCELLED"


def failover_retry_policy(pool_size: int):
    """The retry policy multi-endpoint clients install by default when
    the caller supplied none: a small budget (failover needs attempts to
    spend; the backoff is capped to zero when another endpoint is
    available), with ``CANCELLED`` added to the retryable gRPC codes —
    see :func:`grpc_status_is_endpoint_outage` for why a wire CANCELLED
    is a replica-shutdown signal, and note it is only reachable from an
    actual RpcError, never from local cancellation."""
    from client_tpu.resilience import (
        DEFAULT_RETRYABLE_GRPC_CODES,
        RetryPolicy,
    )

    return RetryPolicy(
        max_attempts=2 * pool_size,
        initial_backoff_s=0.02,
        max_backoff_s=0.5,
        retryable_grpc=frozenset(
            DEFAULT_RETRYABLE_GRPC_CODES | {"CANCELLED"}
        ),
    )


# EWMA smoothing for the per-endpoint latency estimate: ~the last 20
# requests dominate, old incidents decay instead of poisoning the mean
# forever (the "least-EWMA-latency" routing policy input).
EWMA_ALPHA = 0.1

# Status tokens that mean "the endpoint answered and rejected the
# REQUEST" — the caller's fault, not the endpoint's health. These never
# count toward consecutive-error ejection (mirrors the resilience
# layer's client-fault classification; 429 is excluded on purpose — a
# shedding server is under pressure, which IS a health signal).
_CLIENT_FAULT_GRPC = frozenset(
    {
        "INVALID_ARGUMENT",
        "NOT_FOUND",
        "ALREADY_EXISTS",
        "PERMISSION_DENIED",
        "UNAUTHENTICATED",
        "FAILED_PRECONDITION",
        "OUT_OF_RANGE",
        "UNIMPLEMENTED",
    }
)


def _token_is_client_fault(token: str) -> bool:
    tail = token.rsplit(".", 1)[-1]
    if tail.isdigit():
        code = int(tail)
        return 400 <= code < 500 and code != 429
    return tail in _CLIENT_FAULT_GRPC


class Endpoint:
    """One pool member's health + telemetry state.

    Beyond the failover fields, each endpoint carries the live stats the
    routing policies of the scale-out arc consume: ``outstanding`` (the
    least-outstanding / power-of-two-choices signal), ``ewma_latency_s``
    (the latency-aware signal), and error/reroute counters. All are
    updated under the pool lock by :meth:`EndpointPool.begin` /
    :meth:`EndpointPool.finish` / :meth:`EndpointPool.mark_down`.
    """

    __slots__ = (
        "url",
        "circuit_breaker",
        "down_until",
        "ejected_until",
        "was_down",
        "failures",
        "successes",
        "outstanding",
        "ewma_latency_s",
        "errors",
        "consecutive_errors",
        "ejections",
        "reroutes",
        "pinned_streams",
    )

    def __init__(self, url: str, circuit_breaker=None):
        self.url = url
        self.circuit_breaker = circuit_breaker
        self.down_until = 0.0
        # outlier ejection benches an endpoint on its own clock, composing
        # with (not replacing) the mark_down cooldown
        self.ejected_until = 0.0
        # once an endpoint has been marked down, its first use after the
        # cooldown should be a readiness probe, not a real request
        self.was_down = False
        self.failures = 0
        self.successes = 0
        # live telemetry (begin/finish bracket every attempt)
        self.outstanding = 0
        self.ewma_latency_s = 0.0
        self.errors = 0
        self.consecutive_errors = 0
        self.ejections = 0
        self.reroutes = 0
        # open bidirectional streams pinned to this endpoint (counted at
        # open/close, NOT per request — decoupled streams may produce N
        # responses per request so a per-request bracket is ill-defined;
        # routing policies deliberately exclude this from their load
        # signals and it is surfaced for visibility only)
        self.pinned_streams = 0

    def state(self, now: float) -> str:
        """The endpoint's health state at ``now``: ``up`` (serving),
        ``down`` (benched by an unavailability signal), ``ejected``
        (benched by outlier ejection), or ``probe`` (cooldown elapsed,
        awaiting a readiness re-probe before real traffic)."""
        if self.ejected_until and now < self.ejected_until:
            return "ejected"
        if self.down_until and now < self.down_until:
            return "down"
        if self.was_down:
            return "probe"
        return "up"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Endpoint({self.url!r}, down_until={self.down_until})"


class EndpointPool:
    """Health-aware endpoint selection shared by the client surfaces.

    Parameters
    ----------
    urls:
        Endpoint addresses (``host:port``). A single comma-separated
        string is accepted (the perf CLI's ``-u host1:p1,host2:p2``).
    cooldown_s:
        How long a failed endpoint stays out of rotation before it is
        probed again (a server's ``Retry-After`` hint overrides this per
        incident).
    breaker_factory:
        Optional zero-arg callable returning a per-endpoint
        :class:`~client_tpu.resilience.CircuitBreaker`; when set,
        :meth:`pick` skips endpoints whose breaker is open and
        :meth:`observe` feeds each endpoint's breaker.
    routing_policy:
        None (sticky-primary, the default), a policy name
        (``round_robin`` / ``least_outstanding`` / ``p2c`` /
        ``consistent_hash``), or a
        :class:`~client_tpu.lifecycle.routing.RoutingPolicy` instance.
    eject_consecutive_errors / eject_ewma_factor / ejection_cooldown_s:
        Outlier ejection: ``eject_consecutive_errors`` failed attempts
        in a row (0 disables), or an EWMA latency above
        ``eject_ewma_factor`` x the median of the other endpoints'
        EWMAs (0 disables; needs >= 3 endpoints with latency data),
        eject the endpoint for ``ejection_cooldown_s`` — it re-enters
        through the same readiness re-probe as a benched endpoint.
        Ejection never removes the last healthy endpoint.
    clock:
        Injectable monotonic-seconds clock (fake-clock tests).
    logger:
        Optional :class:`~client_tpu.observability.StructuredLogger`.
        When set, failover state changes emit structured events
        (``endpoint_down`` / ``endpoint_ejected`` /
        ``endpoint_recovered``); when None — the default — each site is
        a single None-check (the same zero-cost pattern as the
        resilience layer's attempt-event log).
    """

    def __init__(
        self,
        urls: Union[str, Sequence[str]],
        cooldown_s: float = 1.0,
        breaker_factory: Optional[Callable[[], object]] = None,
        routing_policy=None,
        eject_consecutive_errors: int = 5,
        eject_ewma_factor: float = 4.0,
        ejection_cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        logger=None,
    ):
        if isinstance(urls, str):
            urls = [u.strip() for u in urls.split(",") if u.strip()]
        urls = list(urls)
        if not urls:
            raise ValueError("EndpointPool needs at least one url")
        self.cooldown_s = cooldown_s
        self.eject_consecutive_errors = eject_consecutive_errors
        self.eject_ewma_factor = eject_ewma_factor
        self.ejection_cooldown_s = ejection_cooldown_s
        self._clock = clock
        self._logger = logger
        self._breaker_factory = breaker_factory
        self._lock = threading.Lock()
        self._endpoints: List[Endpoint] = [
            Endpoint(u, breaker_factory() if breaker_factory else None)
            for u in urls
        ]
        self._routing_policy = None
        self._install_policy(resolve_routing_policy(routing_policy))
        self._primary = 0
        # times the primary moved off a failed endpoint (observability)
        self.failovers = 0
        # outlier ejections across the pool (observability)
        self.ejections = 0
        # hedged attempts launched / won by the hedge (fed by the hedge
        # orchestration; exposed as tpu_client_hedges_total downstream)
        self.hedges = 0
        self.hedge_wins = 0

    def _install_policy(self, policy) -> None:
        # consistent-hash rings must cover the FULL membership (health
        # filters at lookup); priming here — before any endpoint can be
        # benched — is what keeps key->endpoint stable across recoveries
        if policy is not None and hasattr(policy, "prime"):
            policy.prime([ep.url for ep in self._endpoints])
        self._routing_policy = policy

    @property
    def routing_policy(self):
        return self._routing_policy

    @routing_policy.setter
    def routing_policy(self, spec) -> None:
        self._install_policy(resolve_routing_policy(spec))

    @property
    def key_parameter(self) -> Optional[str]:
        """The request-parameter name the active policy keys affinity on
        (None unless a consistent-hash policy is installed) — client
        surfaces skip the per-request lookup entirely when None."""
        policy = self._routing_policy
        return policy.key_parameter if policy is not None else None

    @classmethod
    def resolve(
        cls,
        url: Optional[Union[str, "EndpointPool"]] = None,
        urls: Optional[Sequence[str]] = None,
        **kwargs,
    ) -> "EndpointPool":
        """The one spot every client constructor funnels through:
        ``url`` may be a host:port, a comma list, or an EndpointPool
        instance (returned as-is — shareable across clients, though an
        explicit ``routing_policy`` is installed onto it); ``urls``
        wins when given."""
        if isinstance(url, EndpointPool):
            policy = kwargs.get("routing_policy")
            if policy is not None:
                url.routing_policy = policy
            return url
        if urls:
            return cls(urls, **kwargs)
        if url is None:
            raise ValueError("either url or urls is required")
        return cls(url, **kwargs)

    # -- membership ----------------------------------------------------------
    #
    # Client pools are fixed at construction, but the router tier's pool
    # follows the autoscaler: replicas join as they launch and leave as
    # they drain. Both mutations re-prime any keyed policy's ring over
    # the FULL new membership (some keys move on a membership change —
    # that is inherent to consistent hashing, and the vnode ring bounds
    # how many).

    def add_endpoint(self, url: str) -> Endpoint:
        """Add one endpoint to the pool (idempotent: an existing url
        returns its live endpoint untouched, telemetry intact)."""
        with self._lock:
            for ep in self._endpoints:
                if ep.url == url:
                    return ep
            ep = Endpoint(
                url,
                self._breaker_factory() if self._breaker_factory else None,
            )
            self._endpoints.append(ep)
            policy = self._routing_policy
            if policy is not None and hasattr(policy, "prime"):
                policy.prime([e.url for e in self._endpoints])
        if self._logger is not None:
            self._logger.info("endpoint_added", endpoint=url)
        return ep

    def remove_endpoint(self, url: str) -> bool:
        """Remove one endpoint from rotation (a draining replica: the
        autoscaler stops routing to it BEFORE the drain starts, so
        in-flight work finishes and nothing new lands on it). Refuses to
        empty the pool. Returns True when a member was removed."""
        with self._lock:
            for index, ep in enumerate(self._endpoints):
                if ep.url == url:
                    break
            else:
                return False
            if len(self._endpoints) == 1:
                return False
            del self._endpoints[index]
            if self._primary >= len(self._endpoints):
                self._primary = 0
            policy = self._routing_policy
            if policy is not None and hasattr(policy, "prime"):
                policy.prime([e.url for e in self._endpoints])
        if self._logger is not None:
            self._logger.info("endpoint_removed", endpoint=url)
        return True

    # -- introspection -------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self._endpoints)

    @property
    def urls(self) -> List[str]:
        return [ep.url for ep in self._endpoints]

    @property
    def endpoints(self) -> List[Endpoint]:
        return list(self._endpoints)

    @property
    def primary_url(self) -> str:
        with self._lock:
            return self._endpoints[self._primary].url

    def _up(self, ep: Endpoint, now: float) -> bool:
        if ep.down_until and now < ep.down_until:
            return False
        if ep.ejected_until and now < ep.ejected_until:
            return False
        if ep.circuit_breaker is not None and not ep.circuit_breaker.allow():
            return False
        return True

    @staticmethod
    def _benched_until(ep: Endpoint) -> float:
        return max(ep.down_until, ep.ejected_until)

    # -- selection -----------------------------------------------------------

    def pick(
        self,
        key=None,
        exclude: Optional[Endpoint] = None,
        allow=None,
    ) -> Endpoint:
        """The endpoint the next request should target. With a routing
        policy installed, the policy selects among the currently healthy
        endpoints (on their live outstanding/EWMA signals, or on ``key``
        for consistent-hash affinity); without one — or when a keyed
        policy gets no key — the sticky-primary scan applies. ``exclude``
        removes one endpoint from consideration (the hedge path asks for
        somewhere *different*); ``allow`` (a url set, or None for all)
        restricts selection to a subset — the router's model→replica
        table picks only among replicas that serve the request's model.
        When every endpoint is down, returns the one whose cooldown ends
        soonest — callers still try it (the server may be back early)."""
        with self._lock:
            now = self._clock()
            n = len(self._endpoints)
            policy = self._routing_policy

            def eligible(ep):
                return allow is None or ep.url in allow

            if policy is not None:
                candidates = [
                    ep
                    for ep in self._endpoints
                    if ep is not exclude and eligible(ep) and self._up(ep, now)
                ]
                if candidates:
                    choice = policy.select(candidates, key)
                    if choice is not None:
                        return choice
            for offset in range(n):
                ep = self._endpoints[(self._primary + offset) % n]
                if ep is not exclude and eligible(ep) and self._up(ep, now):
                    return ep
            if exclude is not None:
                # nothing else healthy: the excluded endpoint (if up) is
                # all there is — callers detect the identity and skip
                # hedging rather than duplicate onto the same endpoint
                for ep in self._endpoints:
                    if eligible(ep) and self._up(ep, now):
                        return ep
            allowed = [ep for ep in self._endpoints if eligible(ep)]
            return min(allowed or self._endpoints, key=self._benched_until)

    def has_alternative(self, ep: Optional[Endpoint]) -> bool:
        """True when a request that just failed on ``ep`` (None: on
        whichever endpoint was benched for it) has somewhere else to go
        RIGHT NOW — the failover fast path (no backoff sleep)."""
        with self._lock:
            now = self._clock()
            return any(
                other is not ep and self._up(other, now)
                for other in self._endpoints
            )

    def needs_probe(self, ep: Endpoint) -> bool:
        """True when ``ep`` is coming back from a down/ejected period and
        should pass a readiness probe before carrying real traffic.
        Single-endpoint pools never probe — there is no alternative to
        protect."""
        if len(self._endpoints) == 1:
            return False
        with self._lock:
            return ep.was_down and self._clock() >= self._benched_until(ep)

    # -- per-endpoint telemetry ----------------------------------------------

    def begin(self, ep: Endpoint) -> float:
        """Mark one request outstanding on ``ep``; returns the start
        timestamp the caller passes back to :meth:`finish`. Every attempt
        a client surface sends brackets itself with begin/finish, so
        ``outstanding`` is the live in-flight count per endpoint — the
        signal a least-outstanding routing policy selects on."""
        with self._lock:
            ep.outstanding += 1
        return self._clock()

    def finish(
        self,
        ep: Endpoint,
        started: float,
        ok: bool,
        cancelled: bool = False,
        token: Optional[str] = None,
    ) -> float:
        """Close the begin/finish bracket: drop the outstanding count,
        fold a successful attempt's latency into the EWMA, count an
        error; returns the attempt latency in seconds (the hedge trigger
        feeds on it). ``cancelled=True`` (a hedge loser, or a locally
        cancelled attempt) books neither a latency sample nor an error —
        cancellation says nothing about the endpoint.

        Ejection triggers live here: ``eject_consecutive_errors``
        failures in a row, or — on a success — an EWMA that drifted past
        ``eject_ewma_factor`` x the median of the peers' EWMAs (the
        slow-replica outlier: it answers, just too late to wait for).
        ``token`` (the failed attempt's status, when the caller has one)
        keeps *client-fault* responses — 4xx, INVALID_ARGUMENT and kin —
        out of the consecutive-error count entirely: the endpoint
        answered, which proves it healthy, so such a response RESETS the
        streak rather than feeding it (a workload of consistently
        rejected requests must never eject a healthy replica).
        Endpoint-health *benching* signals (503/UNAVAILABLE) stay with
        :meth:`observe`."""
        latency_s = self._clock() - started
        event = None
        with self._lock:
            if ep.outstanding > 0:
                ep.outstanding -= 1
            if cancelled:
                return latency_s
            if ok:
                ep.consecutive_errors = 0
                if ep.ewma_latency_s:
                    ep.ewma_latency_s += EWMA_ALPHA * (
                        latency_s - ep.ewma_latency_s
                    )
                else:
                    ep.ewma_latency_s = latency_s
                event = self._maybe_eject_outlier(ep)
            else:
                ep.errors += 1
                if token is not None and _token_is_client_fault(token):
                    ep.consecutive_errors = 0
                else:
                    ep.consecutive_errors += 1
                    if (
                        self.eject_consecutive_errors
                        and ep.consecutive_errors
                        >= self.eject_consecutive_errors
                    ):
                        event = self._eject(ep, "consecutive_errors")
        if event is not None and self._logger is not None:
            self._logger.warning("endpoint_ejected", **event)
        return latency_s

    def _maybe_eject_outlier(self, ep: Endpoint):
        """EWMA-vs-peer-median ejection check (pool lock held). Needs at
        least two peers with latency data — below that, "slower than the
        median" is just "the two replicas differ"."""
        if not self.eject_ewma_factor or len(self._endpoints) < 3:
            return None
        if not ep.ewma_latency_s:
            return None
        if ep.successes < 10:
            # a cold endpoint's EWMA is one sample deep — a warmup/jit
            # spike would read as an "outlier" and eject a healthy
            # replica before its estimate has decayed toward reality
            return None
        peers = sorted(
            other.ewma_latency_s
            for other in self._endpoints
            if other is not ep and other.ewma_latency_s > 0
        )
        if len(peers) < 2:
            return None
        median = peers[len(peers) // 2]
        if median <= 0 or ep.ewma_latency_s <= self.eject_ewma_factor * median:
            return None
        return self._eject(ep, "ewma_outlier")

    def _eject(self, ep: Endpoint, reason: str):
        """Take ``ep`` out of rotation for the ejection cooldown (pool
        lock held). Returns the structured-log event, or None when the
        ejection was refused (it would have removed the last healthy
        endpoint). Re-entry goes through the same readiness re-probe a
        benched endpoint takes."""
        now = self._clock()
        if ep.ejected_until and now < ep.ejected_until:
            return None  # already ejected; don't inflate the counters
        if not any(
            other is not ep and self._up(other, now)
            for other in self._endpoints
        ):
            return None
        ep.ejected_until = now + self.ejection_cooldown_s
        ep.was_down = True
        ep.consecutive_errors = 0
        ep.ejections += 1
        self.ejections += 1
        n = len(self._endpoints)
        if n > 1 and self._endpoints[self._primary] is ep:
            for offset in range(1, n):
                candidate = (self._primary + offset) % n
                if self._up(self._endpoints[candidate], now):
                    self._primary = candidate
                    self.failovers += 1
                    ep.reroutes += 1
                    break
        return {
            "endpoint": ep.url,
            "reason": reason,
            "cooldown_s": round(self.ejection_cooldown_s, 3),
            "ejections": ep.ejections,
        }

    # -- hedging bookkeeping -------------------------------------------------

    def note_hedge(self) -> None:
        """One hedge attempt launched (tpu_client_hedges_total)."""
        with self._lock:
            self.hedges += 1

    def note_hedge_win(self) -> None:
        """The hedge attempt answered before the primary did."""
        with self._lock:
            self.hedge_wins += 1

    # -- pinned streams ------------------------------------------------------

    def pin_stream(self, ep: Endpoint) -> None:
        """One bidirectional stream opened against ``ep``. Stream traffic
        is counted at the STREAM granularity (decoupled models produce N
        responses per request, so a per-request bracket is ill-defined)
        and is deliberately excluded from the routing policies' load
        signals — it is surfaced in :meth:`snapshot` for visibility."""
        with self._lock:
            ep.pinned_streams += 1

    def unpin_stream(self, ep: Endpoint) -> None:
        with self._lock:
            if ep.pinned_streams > 0:
                ep.pinned_streams -= 1

    def snapshot(self) -> dict:
        """The pool's live telemetry in one consistent read: per-endpoint
        outstanding/EWMA/counters plus the pool-level failover, ejection
        and hedge counts — what the perf report's "Client metrics"
        section prints and what the routing policies consume. Each
        endpoint carries its health ``state`` (``up`` / ``down`` /
        ``ejected`` / ``probe``) so an ejected endpoint is never mistaken
        for a healthy idle one."""
        policy = self._routing_policy
        with self._lock:
            now = self._clock()
            return {
                "primary": self._endpoints[self._primary].url,
                "policy": policy.name if policy is not None else "sticky",
                "failovers": self.failovers,
                "ejections": self.ejections,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "endpoints": [
                    {
                        "url": ep.url,
                        "state": ep.state(now),
                        "outstanding": ep.outstanding,
                        "ewma_latency_us": round(ep.ewma_latency_s * 1e6, 1),
                        "successes": ep.successes,
                        "errors": ep.errors,
                        "marked_down": ep.failures,
                        "ejections": ep.ejections,
                        "reroutes": ep.reroutes,
                        "pinned_streams": ep.pinned_streams,
                        "down": bool(ep.down_until and now < ep.down_until),
                    }
                    for ep in self._endpoints
                ],
            }

    # -- health feedback -----------------------------------------------------

    def mark_down(
        self, ep: Endpoint, cooldown_s: Optional[float] = None
    ) -> None:
        """Take ``ep`` out of rotation for a cooldown and advance the
        primary off it."""
        effective_cooldown = cooldown_s if cooldown_s else self.cooldown_s
        failed_over = None
        with self._lock:
            ep.down_until = self._clock() + effective_cooldown
            ep.was_down = True
            ep.failures += 1
            n = len(self._endpoints)
            if n > 1 and self._endpoints[self._primary] is ep:
                self._primary = (self._primary + 1) % n
                self.failovers += 1
                # traffic that was sticky on ep is rerouted to the new
                # primary from here on — charged to the endpoint that
                # caused the move
                ep.reroutes += 1
                failed_over = self._endpoints[self._primary].url
        if self._logger is not None:
            self._logger.warning(
                "endpoint_down",
                endpoint=ep.url,
                cooldown_s=round(effective_cooldown, 3),
                failures=ep.failures,
                new_primary=failed_over,
                failovers=self.failovers,
            )

    def mark_up(self, ep: Endpoint) -> None:
        with self._lock:
            recovered = ep.was_down
            ep.down_until = 0.0
            ep.ejected_until = 0.0
            ep.was_down = False
            ep.consecutive_errors = 0
        if recovered and self._logger is not None:
            self._logger.info("endpoint_recovered", endpoint=ep.url)

    def observe(
        self,
        ep: Endpoint,
        ok: bool = False,
        token: Optional[str] = None,
        retry_after_s: Optional[float] = None,
    ) -> None:
        """Feed one request outcome: success re-arms the endpoint, an
        unavailability token benches it for ``retry_after_s`` (the
        server's own estimate — a draining server knows its restart time
        better than our default) or ``cooldown_s``. Other tokens (4xx,
        model errors) say nothing about endpoint health."""
        if ok:
            with self._lock:
                actively_ejected = bool(
                    ep.ejected_until and self._clock() < ep.ejected_until
                )
                ep.successes += 1
            if not actively_ejected:
                # a success from an endpoint we EJECTED (an in-flight
                # straggler draining out) must not override the
                # deliberate bench — re-entry is the re-probe path's call
                self.mark_up(ep)
            if ep.circuit_breaker is not None:
                ep.circuit_breaker.record_success()
            return
        if status_is_unavailable(token):
            self.mark_down(ep, cooldown_s=retry_after_s)
            if ep.circuit_breaker is not None:
                ep.circuit_breaker.record_failure()
