"""Client-side endpoint pool: health-aware routing + failover state.

Every client surface accepts an :class:`EndpointPool` (or ``urls=[...]``)
wherever a single ``url`` is accepted today. The pool is pure state — it
owns no sockets and issues no probes itself (the owning client probes
``/v2/health/ready`` / gRPC ``ServerReady`` when the pool says a
recovering endpoint :meth:`needs_probe`), so one implementation serves
all four surfaces and tests drive it with a fake clock
(``tools/clock_lint.py`` covers this package).

Routing is sticky-primary with failover: :meth:`pick` returns the current
primary until a request against it fails with an unavailability signal
(connect error, HTTP 503, gRPC UNAVAILABLE — a draining or dead server),
at which point the endpoint is marked down for ``cooldown_s`` (or the
server's own ``Retry-After`` hint) and the primary advances. Per-endpoint
:class:`~client_tpu.resilience.CircuitBreaker` instances (optional) are
consulted by :meth:`pick` and fed by :meth:`observe`, so a flapping
endpoint fails fast instead of eating a timeout per attempt.
"""

import threading
import time
from typing import Callable, List, Optional, Sequence, Union

from client_tpu.resilience import CONNECTION_ERROR_STATUS

# Status tokens that mean "this endpoint cannot serve right now" — route
# around it. 503 / UNAVAILABLE are what a draining server returns; a
# connection error is what a dead one produces.
UNAVAILABLE_TOKENS = frozenset({"503", "UNAVAILABLE", CONNECTION_ERROR_STATUS})


def status_is_unavailable(token: Optional[str]) -> bool:
    """True when a status token ("503", "StatusCode.UNAVAILABLE",
    "CONNECTION_ERROR") signals an endpoint-level outage."""
    if not token:
        return False
    return token.rsplit(".", 1)[-1] in UNAVAILABLE_TOKENS


# EWMA smoothing for the per-endpoint latency estimate: ~the last 20
# requests dominate, old incidents decay instead of poisoning the mean
# forever (the "least-EWMA-latency" routing policy input).
EWMA_ALPHA = 0.1


class Endpoint:
    """One pool member's health + telemetry state.

    Beyond the failover fields, each endpoint carries the live stats the
    routing policies of the scale-out arc consume: ``outstanding`` (the
    least-outstanding / power-of-two-choices signal), ``ewma_latency_s``
    (the latency-aware signal), and error/reroute counters. All are
    updated under the pool lock by :meth:`EndpointPool.begin` /
    :meth:`EndpointPool.finish` / :meth:`EndpointPool.mark_down`.
    """

    __slots__ = (
        "url",
        "circuit_breaker",
        "down_until",
        "was_down",
        "failures",
        "successes",
        "outstanding",
        "ewma_latency_s",
        "errors",
        "reroutes",
    )

    def __init__(self, url: str, circuit_breaker=None):
        self.url = url
        self.circuit_breaker = circuit_breaker
        self.down_until = 0.0
        # once an endpoint has been marked down, its first use after the
        # cooldown should be a readiness probe, not a real request
        self.was_down = False
        self.failures = 0
        self.successes = 0
        # live telemetry (begin/finish bracket every attempt)
        self.outstanding = 0
        self.ewma_latency_s = 0.0
        self.errors = 0
        self.reroutes = 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Endpoint({self.url!r}, down_until={self.down_until})"


class EndpointPool:
    """Health-aware endpoint selection shared by the client surfaces.

    Parameters
    ----------
    urls:
        Endpoint addresses (``host:port``). A single comma-separated
        string is accepted (the perf CLI's ``-u host1:p1,host2:p2``).
    cooldown_s:
        How long a failed endpoint stays out of rotation before it is
        probed again (a server's ``Retry-After`` hint overrides this per
        incident).
    breaker_factory:
        Optional zero-arg callable returning a per-endpoint
        :class:`~client_tpu.resilience.CircuitBreaker`; when set,
        :meth:`pick` skips endpoints whose breaker is open and
        :meth:`observe` feeds each endpoint's breaker.
    clock:
        Injectable monotonic-seconds clock (fake-clock tests).
    logger:
        Optional :class:`~client_tpu.observability.StructuredLogger`.
        When set, failover state changes emit structured events
        (``endpoint_down`` / ``endpoint_recovered``); when None — the
        default — each site is a single None-check (the same zero-cost
        pattern as the resilience layer's attempt-event log).
    """

    def __init__(
        self,
        urls: Union[str, Sequence[str]],
        cooldown_s: float = 1.0,
        breaker_factory: Optional[Callable[[], object]] = None,
        clock: Callable[[], float] = time.monotonic,
        logger=None,
    ):
        if isinstance(urls, str):
            urls = [u.strip() for u in urls.split(",") if u.strip()]
        urls = list(urls)
        if not urls:
            raise ValueError("EndpointPool needs at least one url")
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._logger = logger
        self._lock = threading.Lock()
        self._endpoints: List[Endpoint] = [
            Endpoint(u, breaker_factory() if breaker_factory else None)
            for u in urls
        ]
        self._primary = 0
        # times the primary moved off a failed endpoint (observability)
        self.failovers = 0

    @classmethod
    def resolve(
        cls,
        url: Optional[Union[str, "EndpointPool"]] = None,
        urls: Optional[Sequence[str]] = None,
        **kwargs,
    ) -> "EndpointPool":
        """The one spot every client constructor funnels through:
        ``url`` may be a host:port, a comma list, or an EndpointPool
        instance (returned as-is — shareable across clients); ``urls``
        wins when given."""
        if isinstance(url, EndpointPool):
            return url
        if urls:
            return cls(urls, **kwargs)
        if url is None:
            raise ValueError("either url or urls is required")
        return cls(url, **kwargs)

    # -- introspection -------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self._endpoints)

    @property
    def urls(self) -> List[str]:
        return [ep.url for ep in self._endpoints]

    @property
    def endpoints(self) -> List[Endpoint]:
        return list(self._endpoints)

    @property
    def primary_url(self) -> str:
        with self._lock:
            return self._endpoints[self._primary].url

    def _up(self, ep: Endpoint, now: float) -> bool:
        if ep.down_until and now < ep.down_until:
            return False
        if ep.circuit_breaker is not None and not ep.circuit_breaker.allow():
            return False
        return True

    # -- selection -----------------------------------------------------------

    def pick(self) -> Endpoint:
        """The endpoint the next request should target: the sticky
        primary when healthy, else the next healthy endpoint in rotation.
        When every endpoint is down, returns the one whose cooldown ends
        soonest — callers still try it (the server may be back early)."""
        with self._lock:
            now = self._clock()
            n = len(self._endpoints)
            for offset in range(n):
                ep = self._endpoints[(self._primary + offset) % n]
                if self._up(ep, now):
                    return ep
            return min(self._endpoints, key=lambda e: e.down_until)

    def has_alternative(self, ep: Optional[Endpoint]) -> bool:
        """True when a request that just failed on ``ep`` (None: on
        whichever endpoint was benched for it) has somewhere else to go
        RIGHT NOW — the failover fast path (no backoff sleep)."""
        with self._lock:
            now = self._clock()
            return any(
                other is not ep and self._up(other, now)
                for other in self._endpoints
            )

    def needs_probe(self, ep: Endpoint) -> bool:
        """True when ``ep`` is coming back from a down period and should
        pass a readiness probe before carrying real traffic. Single-
        endpoint pools never probe — there is no alternative to protect."""
        if len(self._endpoints) == 1:
            return False
        with self._lock:
            return ep.was_down and self._clock() >= ep.down_until

    # -- per-endpoint telemetry ----------------------------------------------

    def begin(self, ep: Endpoint) -> float:
        """Mark one request outstanding on ``ep``; returns the start
        timestamp the caller passes back to :meth:`finish`. Every attempt
        a client surface sends brackets itself with begin/finish, so
        ``outstanding`` is the live in-flight count per endpoint — the
        signal a least-outstanding routing policy selects on."""
        with self._lock:
            ep.outstanding += 1
        return self._clock()

    def finish(self, ep: Endpoint, started: float, ok: bool) -> None:
        """Close the begin/finish bracket: drop the outstanding count,
        fold a successful attempt's latency into the EWMA, count an
        error. Endpoint-health signals (503/UNAVAILABLE benching) stay
        with :meth:`observe` — a 400 is an error here but says nothing
        about endpoint health there."""
        latency_s = self._clock() - started
        with self._lock:
            if ep.outstanding > 0:
                ep.outstanding -= 1
            if ok:
                if ep.ewma_latency_s:
                    ep.ewma_latency_s += EWMA_ALPHA * (
                        latency_s - ep.ewma_latency_s
                    )
                else:
                    ep.ewma_latency_s = latency_s
            else:
                ep.errors += 1

    def snapshot(self) -> dict:
        """The pool's live telemetry in one consistent read: per-endpoint
        outstanding/EWMA/counters plus the pool-level failover count —
        what the perf report's "Client metrics" section prints and what
        the scale-out routing policies will consume."""
        with self._lock:
            now = self._clock()
            return {
                "primary": self._endpoints[self._primary].url,
                "failovers": self.failovers,
                "endpoints": [
                    {
                        "url": ep.url,
                        "outstanding": ep.outstanding,
                        "ewma_latency_us": round(ep.ewma_latency_s * 1e6, 1),
                        "successes": ep.successes,
                        "errors": ep.errors,
                        "marked_down": ep.failures,
                        "reroutes": ep.reroutes,
                        "down": bool(ep.down_until and now < ep.down_until),
                    }
                    for ep in self._endpoints
                ],
            }

    # -- health feedback -----------------------------------------------------

    def mark_down(
        self, ep: Endpoint, cooldown_s: Optional[float] = None
    ) -> None:
        """Take ``ep`` out of rotation for a cooldown and advance the
        primary off it."""
        effective_cooldown = cooldown_s if cooldown_s else self.cooldown_s
        failed_over = None
        with self._lock:
            ep.down_until = self._clock() + effective_cooldown
            ep.was_down = True
            ep.failures += 1
            n = len(self._endpoints)
            if n > 1 and self._endpoints[self._primary] is ep:
                self._primary = (self._primary + 1) % n
                self.failovers += 1
                # traffic that was sticky on ep is rerouted to the new
                # primary from here on — charged to the endpoint that
                # caused the move
                ep.reroutes += 1
                failed_over = self._endpoints[self._primary].url
        if self._logger is not None:
            self._logger.warning(
                "endpoint_down",
                endpoint=ep.url,
                cooldown_s=round(effective_cooldown, 3),
                failures=ep.failures,
                new_primary=failed_over,
                failovers=self.failovers,
            )

    def mark_up(self, ep: Endpoint) -> None:
        with self._lock:
            recovered = ep.was_down
            ep.down_until = 0.0
            ep.was_down = False
        if recovered and self._logger is not None:
            self._logger.info("endpoint_recovered", endpoint=ep.url)

    def observe(
        self,
        ep: Endpoint,
        ok: bool = False,
        token: Optional[str] = None,
        retry_after_s: Optional[float] = None,
    ) -> None:
        """Feed one request outcome: success re-arms the endpoint, an
        unavailability token benches it for ``retry_after_s`` (the
        server's own estimate — a draining server knows its restart time
        better than our default) or ``cooldown_s``. Other tokens (4xx,
        model errors) say nothing about endpoint health."""
        if ok:
            self.mark_up(ep)
            ep.successes += 1
            if ep.circuit_breaker is not None:
                ep.circuit_breaker.record_success()
            return
        if status_is_unavailable(token):
            self.mark_down(ep, cooldown_s=retry_after_s)
            if ep.circuit_breaker is not None:
                ep.circuit_breaker.record_failure()
