"""Pluggable routing policies for :class:`~client_tpu.lifecycle.EndpointPool`.

The pool's per-endpoint telemetry (``outstanding``, ``ewma_latency_s`` —
maintained by the begin/finish brackets every unary attempt takes) was
built as the routing-signal set; a :class:`RoutingPolicy` turns those
signals into a selection. Policies see only the *healthy* candidate list
(the pool has already removed benched/ejected/breaker-open endpoints) and
run under the pool lock, so a policy must never call back into the pool.

Built-in policies (``resolve_routing_policy`` accepts these names, with
``-``/``_`` interchangeable):

``sticky``
    The default and the pre-policy behavior: the pool's sticky-primary
    failover scan (implemented in the pool itself; the resolver returns
    None).
``round_robin``
    Rotate through healthy endpoints; even spread regardless of load.
``least_outstanding``
    The endpoint with the fewest in-flight requests (ties broken by EWMA
    latency, then rotation) — tracks live load directly.
``p2c`` (power of two choices)
    Sample two distinct healthy endpoints at random, take the less
    loaded (outstanding, then EWMA). O(1), avoids the thundering-herd
    a deterministic least-loaded pick causes when many clients share
    the same view.
``consistent_hash``
    Hash a per-request key onto a ring of virtual nodes; the same key
    lands on the same endpoint while it is healthy — request affinity,
    the KV-cache-locality prerequisite. The key rides a request
    parameter (``key_parameter``, default ``"routing_key"``); requests
    without a key fall back to the pool's sticky scan.

Mid-request-stream membership changes are handled by construction: a
ring built from the FULL url list with unhealthy endpoints skipped at
lookup keeps every key whose owner is still healthy exactly where it
was (the stability property the tests assert).
"""

import hashlib
import random
from typing import List, Optional, Sequence, Union


class RoutingPolicy:
    """Selection strategy over the pool's healthy endpoints.

    Subclasses implement :meth:`select`. ``candidates`` is a non-empty
    list of healthy :class:`~client_tpu.lifecycle.Endpoint` objects in
    pool order; ``key`` is the per-request routing key (None unless the
    request carried the policy's ``key_parameter``). Returning None
    tells the pool to fall back to its sticky-primary scan.
    """

    name = "policy"
    # request-parameter name whose value becomes the routing key; None
    # for policies that ignore keys (the client surfaces skip the
    # parameter lookup entirely in that case)
    key_parameter: Optional[str] = None

    def select(self, candidates: Sequence, key=None):
        raise NotImplementedError

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"{type(self).__name__}()"


class RoundRobinPolicy(RoutingPolicy):
    """Rotate through healthy endpoints in pool order."""

    name = "round_robin"

    def __init__(self):
        self._next = 0

    def select(self, candidates: Sequence, key=None):
        choice = candidates[self._next % len(candidates)]
        self._next = (self._next + 1) % (1 << 30)
        return choice


class LeastOutstandingPolicy(RoutingPolicy):
    """The endpoint with the fewest in-flight requests right now.

    Ties break by EWMA latency (prefer the historically faster one),
    then by a rotating index so a fully idle pool still spreads load
    instead of hammering endpoint 0.
    """

    name = "least_outstanding"

    def __init__(self):
        self._tiebreak = 0

    def select(self, candidates: Sequence, key=None):
        self._tiebreak = (self._tiebreak + 1) % (1 << 30)
        n = len(candidates)
        best = None
        best_rank = None
        for offset in range(n):
            ep = candidates[(self._tiebreak + offset) % n]
            rank = (ep.outstanding, ep.ewma_latency_s)
            if best_rank is None or rank < best_rank:
                best, best_rank = ep, rank
        return best


class PowerOfTwoPolicy(RoutingPolicy):
    """Power-of-two-choices: sample two healthy endpoints, take the less
    loaded one (outstanding, then EWMA latency). The randomized pair
    decorrelates many clients making the same decision from the same
    slightly-stale signals.

    ``rng`` is injectable for deterministic tests.
    """

    name = "p2c"

    def __init__(self, rng: Optional[random.Random] = None):
        self._rng = rng if rng is not None else random.Random()

    def select(self, candidates: Sequence, key=None):
        n = len(candidates)
        if n == 1:
            return candidates[0]
        i = self._rng.randrange(n)
        j = self._rng.randrange(n - 1)
        if j >= i:
            j += 1
        a, b = candidates[i], candidates[j]
        if (b.outstanding, b.ewma_latency_s) < (a.outstanding, a.ewma_latency_s):
            return b
        return a


class ConsistentHashPolicy(RoutingPolicy):
    """Consistent-hash affinity on a request parameter.

    A ring of ``vnodes`` virtual nodes per endpoint url maps keys to
    endpoints; the ring is built ONCE from the pool's FULL membership
    (:meth:`prime`, called by the pool when the policy is installed) and
    health is filtered at *lookup*, so endpoint health changes never
    move keys whose owner is still healthy — when an owner is down,
    only its keys move (to the next healthy endpoint clockwise), which
    is the ≥90%-stability property affinity relies on. Building from
    the healthy subset instead would reshuffle unrelated keys when a
    benched endpoint recovered — exactly the churn this policy exists
    to avoid. Keyless requests return None (the pool falls back to its
    sticky scan).
    """

    name = "consistent_hash"

    def __init__(self, key_parameter: str = "routing_key", vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.key_parameter = key_parameter
        self.vnodes = vnodes
        self._ring: List = []  # sorted [(point, url)]
        self._ring_urls: Optional[tuple] = None

    def prime(self, urls: Sequence[str]) -> None:
        """Build the ring from the pool's full membership (the pool
        calls this at install time, BEFORE any endpoint can be benched,
        so the ring always covers every member)."""
        self._build_ring(urls)

    @staticmethod
    def _point(data: str) -> int:
        # placement hash, not cryptography: usedforsecurity=False keeps
        # FIPS-enforced builds from rejecting md5 here
        digest = hashlib.md5(
            data.encode("utf-8"), usedforsecurity=False
        ).digest()
        return int.from_bytes(digest[:8], "big")

    def _build_ring(self, urls: Sequence[str]) -> None:
        ring = []
        for url in urls:
            for i in range(self.vnodes):
                ring.append((self._point(f"{url}#{i}"), url))
        ring.sort()
        self._ring = ring
        self._ring_urls = tuple(sorted(urls))

    def select(self, candidates: Sequence, key=None):
        if key is None:
            return None
        # the ring covers the FULL membership (primed by the pool);
        # health filtering happens at lookup so a benched endpoint's
        # return never reshuffles keys owned by endpoints that stayed
        # healthy
        by_url = {ep.url: ep for ep in candidates}
        urls = tuple(sorted(by_url))
        if self._ring_urls is None or not set(urls) <= set(self._ring_urls):
            # unprimed direct use, or an unknown member appeared:
            # (re)build from what we see (the pool's prime() makes this
            # unreachable in normal operation — pool membership is fixed
            # at construction)
            self._build_ring(urls)
        point = self._point(str(key))
        ring = self._ring
        n = len(ring)
        # binary search for the first ring point >= key point
        lo, hi = 0, n
        while lo < hi:
            mid = (lo + hi) // 2
            if ring[mid][0] < point:
                lo = mid + 1
            else:
                hi = mid
        for offset in range(n):
            url = ring[(lo + offset) % n][1]
            ep = by_url.get(url)
            if ep is not None:
                return ep
        return None


_POLICY_FACTORIES = {
    "sticky": lambda: None,
    "round_robin": RoundRobinPolicy,
    "least_outstanding": LeastOutstandingPolicy,
    "p2c": PowerOfTwoPolicy,
    "power_of_two": PowerOfTwoPolicy,
    "consistent_hash": ConsistentHashPolicy,
}

ROUTING_POLICY_NAMES = (
    "sticky",
    "round_robin",
    "least_outstanding",
    "p2c",
    "consistent_hash",
)


def resolve_routing_policy(
    spec: Union[None, str, RoutingPolicy],
) -> Optional[RoutingPolicy]:
    """One resolver for every ``routing_policy=`` surface: accepts None
    (sticky), a policy name, or a :class:`RoutingPolicy` instance.
    Returns None for sticky — the pool's built-in scan IS that policy."""
    if spec is None or isinstance(spec, RoutingPolicy):
        return spec
    if isinstance(spec, str):
        name = spec.strip().lower().replace("-", "_")
        factory = _POLICY_FACTORIES.get(name)
        if factory is None:
            raise ValueError(
                f"unknown routing policy '{spec}' "
                f"(expected one of {', '.join(ROUTING_POLICY_NAMES)})"
            )
        return factory()
    raise TypeError(
        f"routing_policy must be a name or RoutingPolicy, got {type(spec)!r}"
    )
