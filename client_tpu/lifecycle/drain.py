"""Server-side drain state machine.

A serving process dies gracefully in three steps (the pattern the
reference Triton stack's readiness/liveness split exists to support):

1. SERVING -> DRAINING: readiness goes false (``/v2/health/ready``, gRPC
   ``ServerReady``) while liveness stays true, so load balancers and
   :class:`~client_tpu.lifecycle.EndpointPool` clients stop sending new
   work; new inference requests are rejected with a clean
   503 + ``Retry-After`` / gRPC ``UNAVAILABLE``.
2. In-flight and queued work finishes, up to a configurable drain
   deadline. The controller tracks every admitted request (all four
   ServerCore execution paths), globally and per model, so the drain can
   actually *wait* instead of cancelling futures.
3. DRAINING -> STOPPED: front-ends close. Anything still queued past the
   deadline fails with the same clean unavailability error — never a
   cancelled-future traceback.

No wall-clock reads happen in this module directly (``tools/clock_lint.py``
covers ``client_tpu/lifecycle/``): the clock and async sleep are
injectable, so drain-deadline tests run on fake clocks.
"""

import asyncio
import threading
import time
from typing import Callable, Dict, Optional

from client_tpu.scheduling import SchedulingError

SERVING = "serving"
DRAINING = "draining"
STOPPED = "stopped"
# Not a DrainController state: overlaid on the tpu_server_state gauge by
# the metrics collector while any loaded model's engine is mid-reload
# (self-healing PR 20) — the lifecycle itself stays SERVING so probes
# keep the replica in rotation for its healthy models.
RECOVERING = "recovering"

# tpu_server_state gauge encoding (monotone along the lifecycle;
# RECOVERING sits outside the monotone drain arc)
STATE_VALUES = {SERVING: 0, DRAINING: 1, STOPPED: 2, RECOVERING: 3}


class ServerDrainingError(SchedulingError):
    """Raised for requests arriving while the server is draining/stopped.

    A :class:`~client_tpu.scheduling.SchedulingError` so every wire face
    is already handled: HTTP maps ``http_status``/``retry_after_s`` to a
    503 + ``Retry-After`` response, gRPC maps ``grpc_code`` to
    ``UNAVAILABLE``, and the statistics paths skip double-booking. The
    client resilience layer classifies both faces as retryable, so a
    retry-configured client (or an EndpointPool) rides through a drain.
    """

    http_status = 503
    grpc_code = "UNAVAILABLE"
    reason = "draining"

    def __init__(self, state: str = DRAINING, retry_after_s: float = 1.0):
        super().__init__(
            f"server is {state} and not accepting new inference requests",
            retry_after_s=retry_after_s,
        )


class DrainController:
    """Explicit SERVING -> DRAINING -> STOPPED lifecycle + in-flight census.

    Thread-safe: the admission sites span the event loop (HTTP/grpc.aio
    paths), the native front-end's pump thread (``infer_direct``), and
    executor threads, so the counters live behind a lock.

    ``retry_after_s`` is the backoff hint stamped on drain rejections
    (how long a client without an alternative endpoint should wait before
    retrying — roughly the expected restart time).
    """

    def __init__(
        self,
        retry_after_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        async_sleep: Callable[[float], "asyncio.Future"] = asyncio.sleep,
    ):
        self.retry_after_s = retry_after_s
        self._clock = clock
        self._async_sleep = async_sleep
        self._lock = threading.Lock()
        self._state = SERVING
        self._inflight_total = 0
        self._inflight_by_model: Dict[str, int] = {}
        # drain rejections issued by this controller (observability; the
        # Prometheus counter is booked by the server core)
        self.rejected_total = 0

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def accepting(self) -> bool:
        """True while new inference requests are admitted."""
        with self._lock:
            return self._state == SERVING

    def begin_drain(self) -> None:
        """Stop accepting new work; in-flight work keeps running.

        Idempotent; a STOPPED controller stays stopped."""
        with self._lock:
            if self._state == SERVING:
                self._state = DRAINING

    def resume(self) -> None:
        """Abort a drain (DRAINING -> SERVING). No-op once STOPPED."""
        with self._lock:
            if self._state == DRAINING:
                self._state = SERVING

    def mark_stopped(self) -> None:
        with self._lock:
            self._state = STOPPED

    # -- in-flight census ----------------------------------------------------

    def check(self) -> None:
        """Raise :class:`ServerDrainingError` when not accepting, without
        touching the census (front-end fast paths; the real admission
        happens in :meth:`admit`)."""
        with self._lock:
            if self._state != SERVING:
                self.rejected_total += 1
                raise ServerDrainingError(
                    self._state, retry_after_s=self.retry_after_s
                )

    def admit(self, model_name: str = "") -> None:
        """Gate + count one request. Raises :class:`ServerDrainingError`
        the moment draining starts; otherwise the request is tracked until
        :meth:`finish`."""
        with self._lock:
            if self._state != SERVING:
                self.rejected_total += 1
                raise ServerDrainingError(
                    self._state, retry_after_s=self.retry_after_s
                )
            self._inflight_total += 1
            if model_name:
                self._inflight_by_model[model_name] = (
                    self._inflight_by_model.get(model_name, 0) + 1
                )

    def finish(self, model_name: str = "") -> None:
        """Mark one admitted request complete (success or failure)."""
        with self._lock:
            if self._inflight_total > 0:
                self._inflight_total -= 1
            if model_name:
                count = self._inflight_by_model.get(model_name, 0)
                if count <= 1:
                    self._inflight_by_model.pop(model_name, None)
                else:
                    self._inflight_by_model[model_name] = count - 1

    def snapshot(self) -> Dict[str, object]:
        """One consistent view of the census (state, totals, per-model
        in-flight counts) under a single lock acquisition — the
        ``/v2/debug/state`` building block."""
        with self._lock:
            return {
                "state": self._state,
                "accepting": self._state == SERVING,
                "inflight_total": self._inflight_total,
                "inflight_by_model": dict(self._inflight_by_model),
                "rejected_total": self.rejected_total,
            }

    def inflight(self, model_name: Optional[str] = None) -> int:
        with self._lock:
            if model_name is None:
                return self._inflight_total
            return self._inflight_by_model.get(model_name, 0)

    async def wait_idle(
        self,
        timeout_s: Optional[float] = None,
        model_name: Optional[str] = None,
        poll_s: float = 0.005,
    ) -> bool:
        """Wait until in-flight work (optionally one model's) reaches
        zero; returns False when ``timeout_s`` expires first."""
        deadline = (
            None if timeout_s is None else self._clock() + timeout_s
        )
        while self.inflight(model_name) > 0:
            if deadline is not None and self._clock() >= deadline:
                return False
            await self._async_sleep(poll_s)
        return True
