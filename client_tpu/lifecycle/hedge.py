"""Request hedging: a second attempt for the tail, first response wins.

A hedged call sends the request normally, and — if no response arrived
within the hedge delay — launches ONE duplicate attempt against a
*different* healthy endpoint. Whichever attempt produces an acceptable
response first wins; the loser is cancelled. Hedging trades a small
amount of duplicate work (bounded by the trigger: a p95-derived delay
duplicates ~5% of requests) for a p99 that tracks the fleet's
second-slowest replica instead of its slowest.

Safety rules (enforced by the client surfaces, documented here because
they are the contract):

* Only idempotent requests hedge — sequence inference never does
  (same classification the retry loop uses).
* Requests carrying shm-ring tickets (``shm_ring_region`` parameter)
  never hedge: the slot is a mutable single-writer resource, and two
  servers racing to write one slot would corrupt whichever response
  loses.
* A cancelled loser closes its begin/finish bracket with
  ``cancelled=True`` — it books neither a latency sample nor an error
  in the pool telemetry, and only the winner's outcome reaches the
  retry loop, so hedges are never double-counted in either.

:class:`HedgePolicy` holds the trigger; the orchestration lives in
:func:`hedged_send_async` (asyncio surfaces — http.aio, grpc.aio, and
through them the sync http veneer). The sync gRPC client runs the same
state machine over gRPC futures (see ``_hedged_infer`` there). The
policy is deliberately clock-free: the latency window is fed from the
pool's own begin/finish measurements, so tests drive it with plain
numbers.
"""

import asyncio
import threading
from typing import Callable, List, Optional, Union

from client_tpu.utils import InferenceServerException


class HedgePolicy:
    """When to launch the hedge attempt.

    Parameters
    ----------
    hedge_after_s:
        Fixed hedge delay in seconds. None (the default) derives the
        delay from observed latency instead: the ``quantile`` of a
        rolling window of successful-attempt latencies.
    quantile:
        The derived trigger's quantile (default 0.95 — hedge the
        slowest ~5% of requests).
    min_samples:
        Derived mode stays disarmed (``current_delay_s()`` is None, no
        hedging) until the window holds this many samples — hedging on
        a cold estimate would duplicate half the traffic.
    window:
        Latency-window size in samples (ring buffer).
    min_delay_s:
        Floor for the derived delay; keeps a microsecond-fast model
        from hedging every request that hits one scheduler hiccup.
    """

    def __init__(
        self,
        hedge_after_s: Optional[float] = None,
        quantile: float = 0.95,
        min_samples: int = 20,
        window: int = 512,
        min_delay_s: float = 0.001,
    ):
        if hedge_after_s is not None and hedge_after_s <= 0:
            raise ValueError("hedge_after_s must be > 0 (or None for p95)")
        if not 0.5 <= quantile < 1.0:
            raise ValueError("quantile must be in [0.5, 1.0)")
        if window < 8:
            raise ValueError("window must be >= 8")
        self.hedge_after_s = hedge_after_s
        self.quantile = quantile
        self.min_samples = max(1, min_samples)
        self.min_delay_s = min_delay_s
        self._lock = threading.Lock()
        self._window: List[float] = [0.0] * window
        self._count = 0  # total recorded (ring index = count % window)
        self._cached_delay: Optional[float] = None
        self._cached_at = -1

    def record(self, latency_s: float) -> None:
        """Feed one successful attempt's latency into the window."""
        with self._lock:
            self._window[self._count % len(self._window)] = latency_s
            self._count += 1

    def current_delay_s(self) -> Optional[float]:
        """The hedge delay to use right now; None disarms hedging
        (derived mode still warming up)."""
        if self.hedge_after_s is not None:
            return self.hedge_after_s
        with self._lock:
            if self._count < self.min_samples:
                return None
            # recompute every 16 samples; sorting a 512-entry window per
            # request would cost more than the hedge saves
            if self._cached_delay is None or self._count - self._cached_at >= 16:
                live = sorted(self._window[: min(self._count, len(self._window))])
                index = min(len(live) - 1, int(self.quantile * len(live)))
                self._cached_delay = max(self.min_delay_s, live[index])
                self._cached_at = self._count
            return self._cached_delay

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "mode": (
                    "fixed" if self.hedge_after_s is not None else "derived"
                ),
                "delay_s": self.hedge_after_s
                if self.hedge_after_s is not None
                else self._cached_delay,
                "samples": self._count,
            }


def resolve_hedge_policy(
    spec: Union[None, float, int, str, HedgePolicy],
) -> Optional[HedgePolicy]:
    """One resolver for every ``hedge_policy=`` surface: None (off), a
    :class:`HedgePolicy`, a positive number of seconds (fixed trigger),
    or ``"p95"``/``0`` (latency-derived trigger)."""
    if spec is None or isinstance(spec, HedgePolicy):
        return spec
    if isinstance(spec, str):
        name = spec.strip().lower()
        if name in ("p95", "derived", "auto"):
            return HedgePolicy()
        try:
            spec = float(name)
        except ValueError:
            raise ValueError(
                f"unknown hedge policy '{name}' (expected seconds, 'p95', "
                "or a HedgePolicy)"
            ) from None
    if isinstance(spec, (int, float)):
        if spec == 0:
            return HedgePolicy()  # 0 = derive from observed p95
        return HedgePolicy(hedge_after_s=float(spec))
    raise TypeError(
        f"hedge_policy must be seconds, 'p95', or HedgePolicy, got "
        f"{type(spec)!r}"
    )


async def _run_bracketed(
    pool, hedge, endpoint, send, timeout, value_ok, value_token=None
):
    """One attempt under the pool's begin/finish bracket. Cancellation
    (the hedge loser) closes the bracket with ``cancelled=True`` so the
    outstanding gauge never leaks AND the loser books neither an error
    nor a latency sample. Failure tokens ride into ``finish`` so
    client-fault responses never feed consecutive-error ejection."""
    started = pool.begin(endpoint)
    try:
        value = await send(endpoint, timeout)
    except asyncio.CancelledError:
        pool.finish(endpoint, started, ok=False, cancelled=True)
        raise
    except BaseException as e:
        pool.finish(
            endpoint,
            started,
            ok=False,
            token=e.status()
            if isinstance(e, InferenceServerException)
            else None,
        )
        raise
    ok = value_ok(value) if value_ok is not None else True
    latency_s = pool.finish(
        endpoint,
        started,
        ok=ok,
        token=None
        if ok or value_token is None
        else value_token(value),
    )
    if ok and hedge is not None:
        hedge.record(latency_s)
    return value


async def hedged_send_async(
    pool,
    hedge: HedgePolicy,
    pick: Callable,
    send: Callable,
    attempt_timeout: Optional[float],
    value_ok: Optional[Callable] = None,
    value_token: Optional[Callable] = None,
):
    """One hedged attempt: normal send, plus — past the hedge delay —
    one duplicate on a different endpoint; first acceptable response
    wins, the loser is cancelled.

    ``pick(timeout, exclude)`` is the surface's probe-aware endpoint
    picker (awaitable); ``send(endpoint, timeout)`` performs one raw
    attempt against a SPECIFIC endpoint (no pool bracketing — this
    function owns the brackets); ``value_ok(value)`` classifies in-band
    results (HTTP status tuples) — None means any return value wins.

    From the retry loop's point of view this whole dance is ONE
    attempt: exactly one outcome (the winner's — or, when both fail,
    the primary's) propagates, so hedges never inflate retry counts.
    """
    ep1 = await pick(attempt_timeout, None)
    loop = asyncio.get_running_loop()
    t1 = loop.create_task(
        _run_bracketed(
            pool, hedge, ep1, send, attempt_timeout, value_ok, value_token
        )
    )
    t2 = None
    try:
        delay = hedge.current_delay_s()
        if delay is not None and attempt_timeout is not None:
            delay = min(delay, attempt_timeout)
        if delay is None:
            # derived trigger still warming: plain attempt, feed the window
            return await t1
        done, _pending = await asyncio.wait({t1}, timeout=delay)
        if done:
            return t1.result()
        # the hedge rides what REMAINS of the attempt budget (~delay has
        # elapsed): giving it the full attempt_timeout would let the
        # hedged pair overrun the caller's deadline by up to the delay
        hedge_timeout = (
            max(0.001, attempt_timeout - delay)
            if attempt_timeout is not None
            else None
        )
        ep2 = await pick(hedge_timeout, ep1)
        if ep2 is None or ep2 is ep1:
            # nowhere distinct to hedge to — ride out the primary
            return await t1
        pool.note_hedge()
        t2 = loop.create_task(
            _run_bracketed(
                pool, hedge, ep2, send, hedge_timeout, value_ok, value_token
            )
        )
        outcomes = {}  # task -> ("ok" | "bad", value) | ("err", exc)
        winner = None
        pending = {t1, t2}
        while pending and winner is None:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task.cancelled():
                    outcomes[task] = ("err", asyncio.CancelledError())
                    continue
                exc = task.exception()
                if exc is not None:
                    outcomes[task] = ("err", exc)
                    continue
                value = task.result()
                ok = value_ok(value) if value_ok is not None else True
                outcomes[task] = ("ok" if ok else "bad", value)
            # winner selection is ORDERED (primary first), not the wait
            # set's iteration order: when both land in one wakeup the
            # primary's success wins and hedge_wins stays deterministic
            for task in (t1, t2):
                if outcomes.get(task, ("", None))[0] == "ok":
                    winner = task
                    break
        if winner is not None:
            if winner is t2:
                pool.note_hedge_win()
            return winner.result()
        # both attempts failed: the primary's outcome speaks for the call
        # (one outcome -> one retry-loop classification, never two)
        kind, payload = outcomes[t1]
        if kind == "err":
            raise payload
        return payload
    finally:
        # the loser — and, on external cancellation, both attempts —
        # must never be left running with an open pool bracket
        for task in (t1, t2):
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except BaseException:  # noqa: BLE001 - loser teardown
                    pass
