"""client_tpu — a TPU-native client framework for KServe-v2 inference servers.

A brand-new implementation of the capability surface of the Triton Inference
Server client stack (see SURVEY.md at the repo root), designed JAX-first.
Package layout (built out progressively; see README for current status):

- ``client_tpu.http`` / ``client_tpu.grpc``: sync clients for the KServe v2
  HTTP/REST and gRPC protocols (reference: src/python/library/tritonclient/).
- ``client_tpu.http.aio`` / ``client_tpu.grpc.aio``: asyncio clients. Unlike
  the reference (which bolted aio variants onto sync cores), the asyncio
  implementations here are the primary ones and the sync clients delegate to
  them through a background event loop.
- ``client_tpu.utils``: KServe v2 dtype tables with *native* BF16 (via
  ml_dtypes/jnp.bfloat16 rather than the reference's float32-truncation hack),
  BYTES tensor serialization, and the client exception type.
- ``client_tpu.utils.shared_memory``: POSIX system shared-memory data plane.
- ``client_tpu.utils.tpu_shared_memory``: the TPU replacement for the
  reference's CUDA-IPC data plane — zero-copy jax.Array staging through
  shared pinned host buffers + DLPack.
- ``client_tpu.server``: an in-repo KServe v2 server (HTTP + gRPC) backed by
  JAX models, used for integration tests, benchmarking, and as the in-process
  "no network" backend (the analogue of the reference's triton_c_api backend).
- ``client_tpu.models`` / ``client_tpu.parallel``: JAX model zoo and sharding
  utilities used by the server runtime and benchmarks.
"""

__version__ = "0.1.0"

from client_tpu._client import InferenceServerClientBase  # noqa: F401
from client_tpu._auth import BasicAuth  # noqa: F401
from client_tpu._plugin import InferenceServerClientPlugin  # noqa: F401
from client_tpu._request import Request  # noqa: F401
