"""Repository model type wrapping the continuous-batching engine.

``llm_engine`` is a decoupled KServe v2 model (INPUT_IDS -> one
OUTPUT_IDS token per streamed response — the same wire contract as
``llm_decode``) whose generations share ONE :class:`LlmEngine`: every
concurrent ``execute_decoupled`` call is a sequence in the engine's
running batch, so N concurrent streams cost one batched decode step per
token instead of N serial steps. Served through all streaming surfaces
(decoupled gRPC, OpenAI SSE) untouched — the front-ends just see a
decoupled model.
"""

from typing import Any, AsyncIterator, Dict, Optional

import numpy as np

from client_tpu.llm.engine import EngineConfig, LlmEngine
from client_tpu.server.model_repository import Model
from client_tpu.utils import InferenceServerException


class LlmEngineModel(Model):
    """Continuous-batching LLM generation over the paged KV cache.

    The serving half of ROADMAP item 2: same request/response shape as
    :class:`client_tpu.models.serving.LlmDecodeModel` but backed by the
    shared engine — concurrent generations interleave at every decode
    step rather than running serial single-sequence loops.
    """

    decoupled = True
    max_batch_size = 0
    platform = "jax"
    backend = "jax"
    inputs = [{"name": "INPUT_IDS", "datatype": "INT32", "shape": [-1]}]
    outputs = [{"name": "OUTPUT_IDS", "datatype": "INT32", "shape": [1]}]

    def __init__(
        self,
        name: str = "llm_engine",
        config=None,
        params=None,
        engine_config: Optional[EngineConfig] = None,
    ):
        from client_tpu.models import llama

        self.name = name
        self._config = config or llama.LlamaConfig.tiny(max_seq_len=512)
        if engine_config is None:
            # default pool: 8 full-length sequences' worth of blocks —
            # small enough that sustained overload exercises the
            # queue/preemption path, large enough that the genai-perf
            # default workload (64-token prompts, 16 output tokens)
            # never starves
            block_size = 16
            per_seq = (self._config.max_seq_len + block_size - 1) // block_size
            engine_config = EngineConfig(
                block_size=block_size,
                num_blocks=1 + 8 * per_seq,
                max_active=8,
                max_queue=64,
                max_seq_len=self._config.max_seq_len,
            )
        self.engine_config = engine_config
        self._params = params
        self.engine: Optional[LlmEngine] = None
        self._core = None

    def warmup(self) -> None:
        import jax

        from client_tpu.models import llama

        config = self._config
        if self._params is None:
            self._params = llama.init_params(jax.random.PRNGKey(0), config)
        engine_config = self.engine_config
        params = self._params

        # Buffer donation lets XLA update the block pool in place (the
        # pool is the whole point — ONE physical cache, not a copy per
        # step); the CPU backend does not implement donation and warns,
        # so only donate on real accelerators.
        donate = jax.default_backend() != "cpu"
        prefill = jax.jit(
            lambda tokens, page_table, pages, last_index: (
                llama.prefill_into_pages(
                    params, tokens, page_table, pages, last_index, config
                )
            ),
            donate_argnums=(2,) if donate else (),
        )
        decode = jax.jit(
            lambda tokens, positions, page_tables, pages: (
                llama.decode_step_paged(
                    params, tokens, positions, page_tables, pages, config
                )
            ),
            donate_argnums=(3,) if donate else (),
        )
        pages = llama.init_kv_pages(
            config, engine_config.num_blocks, engine_config.block_size
        )
        # compile the smallest shapes up front (page table all-zeros =
        # every write lands in the reserved trash block)
        max_blocks = engine_config.max_blocks_per_seq
        table = np.zeros([max_blocks], dtype=np.int32)
        logits, pages = prefill(
            np.zeros([1, engine_config.prefill_bucket_min], dtype=np.int32),
            table,
            pages,
            engine_config.prefill_bucket_min - 1,
        )
        logits, pages = decode(
            np.zeros([1], dtype=np.int32),
            np.zeros([1], dtype=np.int32),
            table[None, :],
            pages,
        )
        jax.block_until_ready(logits)
        # a reload replaces the engine wholesale: fresh pool, clean
        # accounting (the old engine's streams were drained by the
        # lifecycle layer before the swap)
        if self.engine is not None:
            self.engine.close()
        self.engine = LlmEngine(
            prefill,
            decode,
            pages,
            engine_config,
            model_name=self.name,
        )
        self._core = None  # rebind metrics/executor after a reload

    def shutdown(self) -> None:
        """Stop the engine's step loop (``ServerCore.close`` hook)."""
        if self.engine is not None:
            self.engine.close()

    def bind_core(self, core) -> None:
        """Wire the engine into the server it serves under (called by
        ``ServerCore.infer_decoupled`` on first use): metrics export via
        the shared registry, device calls on the core's executor, errors
        into the structured logger. Idempotent per core."""
        if self._core is core or self.engine is None:
            return
        self._core = core
        self.engine.metrics = core.metrics
        self.engine._executor = core._executor
        self.engine.logger = core.logger
        self.engine._publish()

    async def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> AsyncIterator[Dict[str, np.ndarray]]:
        if "INPUT_IDS" not in inputs:
            raise InferenceServerException(
                f"model '{self.name}' expects input INPUT_IDS"
            )
        prompt = np.asarray(inputs["INPUT_IDS"], dtype=np.int32).reshape(-1)
        seq = self.engine.submit(prompt.tolist(), parameters=parameters)
        try:
            async for token, final in seq:
                yield {
                    "OUTPUT_IDS": np.array([token], dtype=np.int32),
                    "__final__": final,
                }
        finally:
            # client cancellation / stream teardown: the engine reclaims
            # the sequence's KV blocks within one step-loop iteration
            self.engine.release(seq)
