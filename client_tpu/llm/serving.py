"""Repository model type wrapping the continuous-batching engine.

``llm_engine`` is a decoupled KServe v2 model (INPUT_IDS -> one
OUTPUT_IDS token per streamed response — the same wire contract as
``llm_decode``) whose generations share ONE :class:`LlmEngine`: every
concurrent ``execute_decoupled`` call is a sequence in the engine's
running batch, so N concurrent streams cost one batched decode step per
token instead of N serial steps. Served through all streaming surfaces
(decoupled gRPC, OpenAI SSE) untouched — the front-ends just see a
decoupled model.
"""

import os
from typing import Any, AsyncIterator, Dict, Optional

import numpy as np

from client_tpu.llm.engine import EngineConfig, LlmEngine
from client_tpu.server.model_repository import Model
from client_tpu.utils import InferenceServerException


class LlmEngineModel(Model):
    """Continuous-batching LLM generation over the paged KV cache.

    The serving half of ROADMAP item 2: same request/response shape as
    :class:`client_tpu.models.serving.LlmDecodeModel` but backed by the
    shared engine — concurrent generations interleave at every decode
    step rather than running serial single-sequence loops.
    """

    decoupled = True
    max_batch_size = 0
    platform = "jax"
    backend = "jax"
    inputs = [{"name": "INPUT_IDS", "datatype": "INT32", "shape": [-1]}]
    outputs = [{"name": "OUTPUT_IDS", "datatype": "INT32", "shape": [1]}]

    #: speculative-decoding opt-in (repository model attr): None = off,
    #: else ``{"mode": "draft" | "ngram", "k": N, ...}`` — the knobs of
    #: :func:`client_tpu.llm.speculation.build_proposer`
    speculation: Optional[Dict[str, Any]] = None

    #: engine-fatal auto-recovery (tier 1 of the self-healing stack):
    #: when True, warmup wires an :class:`~client_tpu.llm.recovery.
    #: EngineRecovery` controller onto the engine so a fatal device
    #: failure triggers a bounded-retry background reload instead of
    #: closed-until-manual-reload.  The pod coordinator turns this off
    #: and supervises recovery itself (an engine fatal there usually
    #: means the MESH is broken, which a solo reload cannot fix).
    auto_recovery: bool = True

    #: knobs forwarded to the EngineRecovery constructor (repository
    #: model attr, e.g. ``{"max_attempts": 5, "retry_after_s": 2.0}``)
    recovery_options: Optional[Dict[str, Any]] = None

    def __init__(
        self,
        name: str = "llm_engine",
        config=None,
        params=None,
        engine_config: Optional[EngineConfig] = None,
        speculation: Optional[Dict[str, Any]] = None,
        draft_config=None,
        draft_params=None,
        tp: int = 1,
    ):
        from client_tpu.models import llama

        self.name = name
        # tensor-parallel width: tp > 1 shards params and the paged KV
        # pool over a "tp" mesh axis resolved against the GLOBAL device
        # list — on a pod this is how one engine spans processes
        self.tp = int(tp)
        self.mesh_plan = None
        # pod hook: wraps (prefill, decode, decode_multi) JUST BEFORE the
        # engine is built — after the warmup probes, which every pod
        # member must run unwrapped and in lockstep (the wrapper is where
        # the coordinator broadcasts each step on the bus)
        self.device_fn_wrapper = None
        if speculation is not None:
            self.speculation = dict(speculation)
        elif type(self).speculation is not None:
            self.speculation = dict(type(self).speculation)
        self._draft_config = draft_config
        self._draft_params = draft_params
        self._config = config or llama.LlamaConfig.tiny(max_seq_len=512)
        if engine_config is None:
            # default pool: 8 full-length sequences' worth of blocks —
            # small enough that sustained overload exercises the
            # queue/preemption path, large enough that the genai-perf
            # default workload (64-token prompts, 16 output tokens)
            # never starves
            block_size = 16
            per_seq = (self._config.max_seq_len + block_size - 1) // block_size
            engine_config = EngineConfig(
                block_size=block_size,
                num_blocks=1 + 8 * per_seq,
                max_active=8,
                max_queue=64,
                max_seq_len=self._config.max_seq_len,
            )
        # admission math must see the speculative lookahead the engine
        # will actually use (worst-case K+1 growth per sequence)
        if self.speculation is not None:
            engine_config.spec_k = max(1, int(self.speculation.get("k", 4)))
        self.engine_config = engine_config
        self._params = params
        self.engine: Optional[LlmEngine] = None
        # which ragged paged-attention implementation warmup selected
        # ("pallas" / "pallas_interpret" / "fused_xla" / "standin");
        # reported in the model config's parameters map
        self.decode_kernel: Optional[str] = None
        self._core = None
        # one recovery controller per model instance, created lazily by
        # the first warmup and re-attached across engine swaps
        self._recovery = None

    def _build_device_fns(self, params, config, engine_config, attn,
                          attn_mq, donate):
        """The engine's jitted device callables for one attention
        implementation: (prefill, decode, decode_multi). ``prefill``
        routes start==0 (no shared prefix) through the untouched
        full-prompt path and block-aligned suffixes through
        ``prefill_suffix_into_pages`` with a STATIC power-of-two
        prefix-gather bucket (bounded recompiles, one program per
        (suffix bucket, prefix bucket) pair). ``decode_multi`` (the
        speculative verify step; None when the model does not opt in)
        rides the multi-query twin of the same attention kernel.

        Under a tp mesh plan (``self.mesh_plan``) the same callables are
        built sharded: host args are placed as REPLICATED global arrays
        (on a pod, ``jax.device_put`` cannot reach other processes'
        devices — ``place_global`` can), logits are pinned replicated so
        every process can read them locally, and the page pool keeps its
        kv-head sharding end to end."""
        import jax

        from client_tpu.models import llama

        plan = self.mesh_plan
        jit_out = {}
        rep = None
        if plan is not None:
            from client_tpu.parallel import TP_AXIS

            rep = plan.replicated()
            pages_sharding = plan.sharding(None, None, TP_AXIS, None)
            jit_out = {"out_shardings": (rep, pages_sharding)}

        def _host(value, dtype=np.int32):
            array = np.asarray(value, dtype=dtype)
            if plan is None:
                return array
            from client_tpu.parallel.executor import place_global

            return place_global(array, rep)

        # params ride as an explicit jit argument (not a closure): a
        # process-spanning param pytree cannot be closed over — jax
        # forbids baking non-addressable arrays into the jaxpr as
        # constants — and the argument form is identical for the
        # single-process case
        donate_kw = {"donate_argnums": (3,)} if donate else {}
        prefill_full = jax.jit(
            lambda params_, tokens, page_table, pages, last_index: (
                llama.prefill_into_pages(
                    params_, tokens, page_table, pages, last_index, config
                )
            ),
            **donate_kw,
            **jit_out,
        )
        prefill_suffix = jax.jit(
            lambda params_, tokens, page_table, pages, last_index, start_index, prefix_blocks: (  # noqa: E501
                llama.prefill_suffix_into_pages(
                    params_, tokens, page_table, pages, last_index,
                    start_index, prefix_blocks, config,
                )
            ),
            static_argnums=(6,),
            **donate_kw,
            **jit_out,
        )
        block_size = engine_config.block_size

        def prefill(tokens, page_table, pages, last_index, start_index):
            tokens = _host(tokens)
            page_table = _host(page_table)
            last = (
                _host(np.int32(last_index)) if plan is not None
                else last_index
            )
            if not start_index:
                return prefill_full(params, tokens, page_table, pages, last)
            from client_tpu.llm.engine import block_bucket

            needed = start_index // block_size
            prefix_blocks = min(
                block_bucket(needed), engine_config.max_blocks_per_seq
            )
            return prefill_suffix(
                params, tokens, page_table, pages, last,
                _host(np.int32(start_index)), prefix_blocks,
            )

        donate_kw = {"donate_argnums": (4,)} if donate else {}
        if attn is None:
            decode_jit = jax.jit(
                lambda params_, tokens, positions, page_tables, pages: (
                    llama.decode_step_paged(
                        params_, tokens, positions, page_tables, pages, config
                    )
                ),
                **donate_kw,
                **jit_out,
            )
        else:
            decode_jit = jax.jit(
                lambda params_, tokens, positions, page_tables, pages: (
                    llama.decode_step_paged_attn(
                        params_, tokens, positions, page_tables, pages,
                        config, attn,
                    )
                ),
                **donate_kw,
                **jit_out,
            )

        def decode(tokens, positions, page_tables, pages):
            return decode_jit(
                params, _host(tokens), _host(positions),
                _host(page_tables), pages,
            )

        decode_multi = None
        if attn_mq is not None:
            donate_kw = {"donate_argnums": (5,)} if donate else {}
            decode_multi_jit = jax.jit(
                lambda params_, tokens, positions, lengths, page_tables, pages: (  # noqa: E501
                    llama.decode_step_paged_multi(
                        params_, tokens, positions, lengths, page_tables,
                        pages, config, attn_mq,
                    )
                ),
                **donate_kw,
                **jit_out,
            )

            def decode_multi(tokens, positions, lengths, page_tables, pages):
                return decode_multi_jit(
                    params, _host(tokens), _host(positions), _host(lengths),
                    _host(page_tables), pages,
                )

        return prefill, decode, decode_multi

    def _resolve_tp_plan(self, config):
        """Validate + resolve the ``{"tp": N}`` mesh for this model.
        Raises :class:`InferenceServerException` (a load failure) when
        the head counts don't divide or the devices aren't there."""
        from client_tpu.parallel import TP_AXIS, sharding as mesh_sharding

        if config.n_heads % self.tp or config.n_kv_heads % self.tp:
            raise InferenceServerException(
                f"tp={self.tp} must divide n_heads={config.n_heads} and "
                f"n_kv_heads={config.n_kv_heads}"
            )
        try:
            spec = mesh_sharding.MeshSpec.parse({"axes": {TP_AXIS: self.tp}})
            return mesh_sharding.resolve(spec)
        except (
            mesh_sharding.MeshDeclarationError,
            mesh_sharding.MeshUnavailableError,
        ) as e:
            raise InferenceServerException(str(e)) from e

    def _shard_params(self, params, config, plan):
        """Place the param pytree onto the tp mesh per
        ``llama.param_specs`` (global placement: works whether or not
        the mesh spans processes)."""
        import jax
        from jax.sharding import PartitionSpec

        from client_tpu.models import llama
        from client_tpu.parallel.executor import place_global

        shardings = jax.tree_util.tree_map(
            lambda entries: plan.sharding(*entries),
            llama.param_specs(config),
            is_leaf=lambda node: isinstance(node, PartitionSpec),
        )
        return jax.tree_util.tree_map(
            lambda leaf, sharding: place_global(np.asarray(leaf), sharding),
            params,
            shardings,
        )

    def _shard_pages(self, pages, plan):
        """Shard every layer's (k_pages, v_pages) pool on the kv-head
        axis — the tp partitioning of the paged cache itself."""
        import jax

        from client_tpu.parallel import TP_AXIS
        from client_tpu.parallel.executor import place_global

        sharding = plan.sharding(None, None, TP_AXIS, None)
        return jax.tree_util.tree_map(
            lambda pool: place_global(np.asarray(pool), sharding), pages
        )

    def warmup(self) -> None:
        import jax

        from client_tpu.models import llama, paged_attention

        config = self._config
        if self._params is None:
            self._params = llama.init_params(jax.random.PRNGKey(0), config)
        engine_config = self.engine_config
        params = self._params
        plan = None
        if self.tp > 1:
            # resolve the tp mesh against the GLOBAL device list (on a
            # pod that is every member's devices) and shard the params
            # along llama.param_specs; failures here are load failures
            # with operator-grade reasons, never a 500 at first infer
            plan = self._resolve_tp_plan(config)
            self.mesh_plan = plan
            params = self._shard_params(params, config, plan)
        else:
            self.mesh_plan = None

        # Buffer donation lets XLA update the block pool in place (the
        # pool is the whole point — ONE physical cache, not a copy per
        # step); the CPU backend does not implement donation and warns,
        # so only donate on real accelerators.
        donate = jax.default_backend() != "cpu"
        # kernel selection: env override > platform preference, probed by
        # actually compiling+running the smallest shapes — a backend that
        # cannot serve this host falls down the chain at WARMUP, never at
        # request time. The survivor is reported in the model config.
        preferred, _ = paged_attention.resolve_decode_attention(
            os.environ.get("CLIENT_TPU_LLM_KERNEL"), jax.default_backend()
        )
        candidates = [preferred]
        for fallback in ("fused_xla", "standin"):
            if fallback not in candidates:
                candidates.append(fallback)
        max_blocks = engine_config.max_blocks_per_seq
        table = np.zeros([max_blocks], dtype=np.int32)
        last_error: Optional[Exception] = None
        prefill = decode = decode_multi = pages = None
        for name in candidates:
            attn = (
                None if name == "standin"
                else paged_attention.get_attention_impl(name)
            )
            # speculative verify rides the SAME kernel choice: every
            # implementation has a multi-query twin, and a kernel whose
            # mq variant cannot compile falls down the chain as a whole
            # (decode and verify must agree numerically)
            attn_mq = (
                paged_attention.get_attention_impl_mq(name)
                if self.speculation is not None
                else None
            )
            # under tp the kernel runs per-shard via shard_map (GSPMD
            # cannot partition a pallas_call; for the XLA variants the
            # wrap pins the no-communication head partitioning). The
            # standin path (attn=None, inline attention) is left to
            # GSPMD propagation — it is plain XLA throughout.
            if plan is not None and attn is not None:
                attn = paged_attention.make_tp_attention(attn, plan.mesh)
            if plan is not None and attn_mq is not None:
                attn_mq = paged_attention.make_tp_attention(
                    attn_mq, plan.mesh, multi_query=True
                )
            try:
                prefill, decode, decode_multi = self._build_device_fns(
                    params, config, engine_config, attn, attn_mq, donate
                )
                # fresh pool per attempt: a candidate that failed after
                # donation may have consumed the previous buffers
                pages = llama.init_kv_pages(
                    config, engine_config.num_blocks, engine_config.block_size
                )
                if plan is not None:
                    pages = self._shard_pages(pages, plan)
                # probe the shapes the engine actually serves (page
                # table all-zeros = every write lands in the reserved
                # trash block): full prefill at the smallest bucket, the
                # ragged decode at block buckets 1 AND multi-block (a
                # kernel whose tiling only breaks at wider widths must
                # fall down the chain HERE, not engine-fatally at
                # request time), and — when sharing is on — one suffix
                # prefill so the shared-prefix path is both validated
                # and pre-compiled before the first hit.
                probe_tokens = np.zeros(
                    [1, engine_config.prefill_bucket_min], dtype=np.int32
                )
                logits, pages = prefill(
                    probe_tokens,
                    table,
                    pages,
                    engine_config.prefill_bucket_min - 1,
                    0,
                )
                if engine_config.prefix_sharing and max_blocks > 1:
                    logits, pages = prefill(
                        probe_tokens,
                        table,
                        pages,
                        engine_config.prefill_bucket_min - 1,
                        engine_config.block_size,
                    )
                for nb in {1, min(8, max_blocks)}:
                    logits, pages = decode(
                        np.zeros([1], dtype=np.int32),
                        np.zeros([1], dtype=np.int32),
                        table[None, :nb],
                        pages,
                    )
                if decode_multi is not None:
                    # probe the verify shape too (T=2: one real token +
                    # one draft) — all writes land in the trash block
                    logits, pages = decode_multi(
                        np.zeros([1, 2], dtype=np.int32),
                        np.zeros([1, 2], dtype=np.int32),
                        np.zeros([1], dtype=np.int32),
                        table[None, :1],
                        pages,
                    )
                jax.block_until_ready(logits)
                self.decode_kernel = name
                break
            except Exception as e:  # noqa: BLE001 - fall down the chain
                last_error = e
                prefill = decode = decode_multi = pages = None
        if decode is None:
            raise InferenceServerException(
                f"no paged-attention kernel usable on this host: {last_error}"
            ) from last_error
        proposer = None
        if self.speculation is not None:
            from client_tpu.llm.speculation import build_proposer

            draft_params, draft_config = self._draft_params, self._draft_config
            if self.speculation.get("draft") == "self":
                # the draft IS the target (self-speculation): the
                # near-100%-acceptance regime that measures the verify
                # machinery's ceiling — proposals cost a full target
                # forward, so this is a bench/diagnostic mode, not a
                # production speedup config
                draft_params, draft_config = params, config
            # a malformed speculation declaration fails HERE (warmup is
            # the model-load error surface), never at request time
            proposer = build_proposer(
                self.speculation,
                target_config=config,
                draft_params=draft_params,
                draft_config=draft_config,
            )
        # followers (pod workers) drive these directly off the bus; the
        # tuple is captured BEFORE any wrapper so a worker's handlers
        # never re-broadcast
        self._device_fns = (prefill, decode, decode_multi)
        if self.device_fn_wrapper is not None:
            # pod coordinator hook: wrap AFTER the probes (which every
            # member ran unwrapped, in lockstep) so only real engine
            # steps ride the bus
            prefill, decode, decode_multi = self.device_fn_wrapper(
                prefill, decode, decode_multi
            )
        # a reload replaces the engine wholesale: fresh pool, clean
        # accounting (the old engine's streams were drained by the
        # lifecycle layer before the swap)
        if self.engine is not None:
            self.engine.close()
        self.engine = LlmEngine(
            prefill,
            decode,
            pages,
            engine_config,
            model_name=self.name,
            decode_multi_fn=decode_multi,
            proposer=proposer,
        )
        self._core = None  # rebind metrics/executor after a reload
        self._wire_recovery()

    def _wire_recovery(self) -> None:
        """Attach the auto-recovery controller to the (possibly brand
        new) engine.  The controller itself re-attaches after ITS
        reloads; this covers the initial warmup and manual reloads."""
        if not self.auto_recovery:
            return
        if self._recovery is None:
            from client_tpu.llm.recovery import EngineRecovery

            self._recovery = EngineRecovery(
                self, **dict(self.recovery_options or {})
            )
        self._recovery.attach(self.engine)

    def reload(self) -> None:
        """Rebuild device state from scratch: fresh KV pool, re-probed
        kernels, a new engine.  Calls :meth:`warmup` through the CLASS
        so the pod coordinator's instance-level warmup pin (the lockstep
        no-op) never swallows a real reload."""
        type(self).warmup(self)

    @property
    def recovering(self) -> bool:
        """True while a background engine reload is in flight (surfaced
        in ``debug_state()`` and the ``tpu_server_state`` overlay)."""
        from client_tpu.llm import recovery

        return (
            self._recovery is not None
            and self._recovery.state == recovery.RECOVERING
        )

    def config(self) -> Dict[str, Any]:
        """Model config with the warmup-selected decode kernel, the
        prefix-sharing mode, and the speculation declaration in the
        parameters map (Triton ModelParameter wire shape — both
        protocols surface it, like the mesh topology does for sharded
        models).

        ``speculation_stats`` carries the engine's LIVE speculation
        counters as a JSON string: the proto statistics schema is
        frozen, so the config parameters map is the one schemaless
        channel a remote harness (genai-perf ``--json-summary``) can
        delta before/after a run to report tokens-per-step and
        acceptance rate over exactly that run."""
        import json

        doc = super().config()
        parameters = doc.setdefault("parameters", {})
        parameters["decode_kernel"] = {
            "string_value": self.decode_kernel or "uninitialized"
        }
        parameters["tp"] = {"string_value": str(self.tp)}
        parameters["prefix_sharing"] = {
            "string_value": (
                "cow" if self.engine_config.prefix_sharing else "off"
            )
        }
        if self.speculation is None:
            parameters["speculation"] = {"string_value": "off"}
        else:
            parameters["speculation"] = {
                "string_value": json.dumps(
                    self.speculation, sort_keys=True
                )
            }
            if self.engine is not None:
                stats = self.engine.stats()
                parameters["speculation_stats"] = {
                    "string_value": json.dumps(
                        {
                            key: stats[key]
                            for key in (
                                "steps",
                                "lane_steps",
                                "step_tokens",
                                "spec_steps",
                                "spec_proposed",
                                "spec_accepted",
                            )
                        },
                        sort_keys=True,
                    )
                }
        return doc

    def shutdown(self) -> None:
        """Stop the engine's step loop (``ServerCore.close`` hook)."""
        if self.engine is not None:
            self.engine.close()

    def bind_core(self, core) -> None:
        """Wire the engine into the server it serves under (called by
        ``ServerCore.infer_decoupled`` on first use): metrics export via
        the shared registry, device calls on the core's executor, errors
        into the structured logger. Idempotent per core."""
        if self._core is core or self.engine is None:
            return
        self._core = core
        self.engine.metrics = core.metrics
        self.engine._executor = core._executor
        self.engine.logger = core.logger
        self.engine._publish()

    async def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> AsyncIterator[Dict[str, np.ndarray]]:
        if "INPUT_IDS" not in inputs:
            raise InferenceServerException(
                f"model '{self.name}' expects input INPUT_IDS"
            )
        prompt = np.asarray(inputs["INPUT_IDS"], dtype=np.int32).reshape(-1)
        seq = self.engine.submit(prompt.tolist(), parameters=parameters)
        try:
            async for token, final in seq:
                yield {
                    "OUTPUT_IDS": np.array([token], dtype=np.int32),
                    "__final__": final,
                }
        finally:
            # client cancellation / stream teardown: the engine reclaims
            # the sequence's KV blocks within one step-loop iteration
            self.engine.release(seq)
