"""LLM serving engine: continuous batching + paged KV cache + streaming.

- :mod:`client_tpu.llm.kv_cache` — block-allocated paged KV accounting
  (fixed-size token blocks, allocate-on-demand, capacity admission).
- :mod:`client_tpu.llm.engine` — iteration-level scheduler: prefill/decode
  split, per-step join/exit, preemption under cache pressure, token
  streaming handles.
- :mod:`client_tpu.llm.serving` — the ``llm_engine`` repository model
  serving the engine through the decoupled gRPC and OpenAI SSE paths.

Clock-injected throughout (tools/clock_lint.py covers this package).
"""

from client_tpu.llm.engine import EngineConfig, LlmEngine, Sequence
from client_tpu.llm.kv_cache import (
    TRASH_BLOCK,
    BlockAllocator,
    CacheCapacityError,
)

__all__ = [
    "BlockAllocator",
    "CacheCapacityError",
    "EngineConfig",
    "LlmEngine",
    "Sequence",
    "TRASH_BLOCK",
]
