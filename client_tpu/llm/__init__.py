"""LLM serving engine: continuous batching + paged KV cache + streaming.

- :mod:`client_tpu.llm.kv_cache` — block-allocated paged KV accounting
  (fixed-size token blocks, allocate-on-demand, capacity admission).
- :mod:`client_tpu.llm.engine` — iteration-level scheduler: prefill/decode
  split, per-step join/exit, preemption under cache pressure, token
  streaming handles.
- :mod:`client_tpu.llm.serving` — the ``llm_engine`` repository model
  serving the engine through the decoupled gRPC and OpenAI SSE paths.
- :mod:`client_tpu.llm.speculation` — draft proposers (n-gram prompt
  lookup, draft-model rollout) for speculative decoding; the engine
  verifies their candidates in one multi-query paged-attention call.

Clock-injected throughout (tools/clock_lint.py covers this package).
"""

from client_tpu.llm.engine import (
    EngineConfig,
    EngineRecoveringError,
    LlmEngine,
    Sequence,
)
from client_tpu.llm.recovery import EngineRecovery
from client_tpu.llm.kv_cache import (
    TRASH_BLOCK,
    BlockAllocator,
    CacheCapacityError,
)
from client_tpu.llm.speculation import (
    DraftModelProposer,
    NgramProposer,
    build_proposer,
)

__all__ = [
    "BlockAllocator",
    "CacheCapacityError",
    "DraftModelProposer",
    "EngineConfig",
    "EngineRecovery",
    "EngineRecoveringError",
    "LlmEngine",
    "NgramProposer",
    "Sequence",
    "TRASH_BLOCK",
    "build_proposer",
]
