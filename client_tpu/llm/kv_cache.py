"""Block-allocated paged KV-cache accounting with copy-on-write sharing.

The manager half of the paged cache (the physical pool lives in
``models/llama.py`` ``init_kv_pages``): a fixed population of
``block_size``-token blocks handed out on demand, one logical page table
per live sequence. Capacity is the admission signal — a full pool QUEUES
new work (the engine keeps it waiting) instead of OOMing a growing dense
cache, and freeing on completion/cancellation returns blocks for the next
admission. Physical block 0 is reserved as the trash block padding lanes
write into, so it is never allocated.

Prefix sharing (ROADMAP item 2, PR-14): every physical block carries a
REFCOUNT, and full prompt blocks are content-hashed into a shared index.
The hash of block ``i`` chains over everything before it
(``hash(prev_hash, block_tokens)``), because a block's K/V values depend
on its entire causal prefix, not just its own tokens — two blocks are
interchangeable iff their chains match. A new sequence whose prompt
chain-matches the index *references* the existing blocks instead of
allocating and recomputing them (the engine then prefills only the
unshared suffix). Copy-on-write discipline: a shared block is never
written in place and never reclaimed while ``refcount > 1`` — writers
always target fresh blocks (:meth:`extend` never returns a shared
block), and :meth:`free` only returns a block to the pool when its LAST
reference drops, unpublishing it from the index in the same breath
(refcount==0 means reclaimed, nothing lingers).

Pure bookkeeping: no clocks, no jax, single-owner (the engine's step
loop) — no locks.
"""

import hashlib
from typing import Dict, Iterable, List, Sequence, Tuple

from client_tpu.utils import InferenceServerException

# Reserved physical block: bucketed-batch padding lanes and padded
# prompt tails scatter their K/V here; page-table entries of 0 mean
# "unallocated" and are masked out of attention.
TRASH_BLOCK = 0

# chain seed: makes the empty-prefix digest explicit
_CHAIN_SEED = b"kv-block-chain"


class CacheCapacityError(InferenceServerException):
    """A block demand exceeded the pool's free (or total) capacity."""

    def __init__(self, msg: str):
        super().__init__(msg, status="RESOURCE_EXHAUSTED")


class BlockAllocator:
    """Fixed-size-block pool accounting for the paged KV cache.

    ``num_blocks`` counts PHYSICAL blocks including the reserved trash
    block; :attr:`capacity` (= ``num_blocks - 1``) is what sequences can
    actually hold. Blocks are identified by pool index; a block may be
    referenced by several sequences at once (shared prefix), and returns
    to the pool only when the last reference is freed.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is reserved)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free stack: recently-freed blocks are re-issued first
        # (their pages are hot in cache)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: Dict[object, List[int]] = {}
        self._ref: Dict[int, int] = {}  # phys -> live reference count
        self._index: Dict[bytes, int] = {}  # chain digest -> phys
        self._hash_of: Dict[int, bytes] = {}  # phys -> its published digest
        # cumulative sharing counters (the engine mirrors them to metrics)
        self.prefix_hits = 0  # blocks whose prefill was skipped
        self.prefix_queries = 0  # allocations that consulted the index

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the trash block excluded)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        """Distinct PHYSICAL blocks allocated — sharing keeps this low."""
        return self.capacity - len(self._free)

    @property
    def blocks_shared(self) -> int:
        """Physical blocks currently referenced by more than one
        sequence (each is at least one whole prefill-block of compute
        and memory saved)."""
        return sum(1 for count in self._ref.values() if count >= 2)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` of context."""
        return (max(0, n_tokens) + self.block_size - 1) // self.block_size

    def refcount(self, phys: int) -> int:
        """Live references to a physical block (0 = free/unallocated)."""
        return self._ref.get(phys, 0)

    def owned(self, seq_id) -> List[int]:
        """The sequence's block list (allocation order = logical order)."""
        return self._owned.get(seq_id, [])

    # -- prefix hashing / matching ------------------------------------------

    def chain_hashes(self, tokens: Sequence[int]) -> List[bytes]:
        """Chained sha256 digests of every FULL block of ``tokens``
        (block ``i``'s digest covers tokens ``0 .. (i+1)*block_size``).

        Cryptographic on purpose: a collision here would silently serve
        one prompt's K/V to a DIFFERENT prompt (wrong completions +
        cross-request prompt influence), so a 64-bit ``hash()`` chain is
        not acceptable identity for content-addressed cache blocks."""
        digest = hashlib.sha256(
            _CHAIN_SEED + self.block_size.to_bytes(4, "little")
        ).digest()
        out: List[bytes] = []
        for i in range(len(tokens) // self.block_size):
            block = tokens[i * self.block_size:(i + 1) * self.block_size]
            h = hashlib.sha256(digest)
            h.update(
                b"".join(
                    int(t).to_bytes(8, "little", signed=True) for t in block
                )
            )
            digest = h.digest()
            out.append(digest)
        return out

    def match_count(self, hashes: Iterable[bytes]) -> int:
        """Longest indexed prefix (in blocks) — a side-effect-free probe
        for admission math; no references are taken."""
        n = 0
        for h in hashes:
            if h not in self._index:
                break
            n += 1
        return n

    # -- allocation ----------------------------------------------------------

    def allocate(self, seq_id, n_blocks: int) -> List[int]:
        """Claim ``n_blocks`` for a new sequence; all-or-nothing."""
        blocks, _ = self.allocate_shared(seq_id, n_blocks, ())
        return blocks

    def allocate_shared(
        self, seq_id, n_blocks: int, prefix_hashes: Sequence[bytes]
    ) -> Tuple[List[int], int]:
        """Claim ``n_blocks``, referencing indexed blocks for the longest
        matching prefix of ``prefix_hashes`` and allocating the rest
        fresh. All-or-nothing: on :class:`CacheCapacityError` no
        reference has been taken. Returns ``(blocks, n_matched)`` —
        ``blocks[:n_matched]`` are shared (read-only for this sequence),
        the rest are exclusively owned. The returned list never aliases
        the ownership record."""
        if seq_id in self._owned:
            raise CacheCapacityError(
                f"sequence {seq_id!r} already owns blocks"
            )
        matched: List[int] = []
        for h in prefix_hashes:
            if len(matched) >= n_blocks:
                break
            phys = self._index.get(h)
            if phys is None:
                break
            matched.append(phys)
        need_new = n_blocks - len(matched)
        if need_new > len(self._free):
            raise CacheCapacityError(
                f"KV cache exhausted: need {need_new} blocks "
                f"({n_blocks} minus {len(matched)} shared), "
                f"{len(self._free)} of {self.capacity} free"
            )
        if prefix_hashes:
            self.prefix_queries += 1
            self.prefix_hits += len(matched)
        for phys in matched:
            self._ref[phys] += 1
        fresh = [self._free.pop() for _ in range(need_new)]
        for phys in fresh:
            self._ref[phys] = 1
        blocks = matched + fresh
        self._owned[seq_id] = blocks
        # a copy: callers keep their own page-table mirror, and a caller
        # appending to the returned list must not alias the ownership
        # record (a block listed twice would be freed twice)
        return list(blocks), len(matched)

    def extend(self, seq_id) -> int:
        """Claim ONE more block for a growing sequence (decode entering a
        new block); raises :class:`CacheCapacityError` when the pool is
        dry — the engine's preemption signal. Always a FRESH block with
        refcount 1: growth never writes into shared storage."""
        if seq_id not in self._owned:
            raise CacheCapacityError(f"sequence {seq_id!r} owns no blocks")
        if not self._free:
            raise CacheCapacityError(
                f"KV cache exhausted: 0 of {self.capacity} blocks free"
            )
        block = self._free.pop()
        self._ref[block] = 1
        self._owned[seq_id].append(block)
        return block

    def truncate(self, seq_id, keep: int) -> int:
        """Give back a sequence's TRAILING blocks beyond its first
        ``keep`` (speculative-decode rollback: lookahead blocks claimed
        for draft-token writes that verification then rejected).

        Only ever legal on exclusively-owned tail blocks — growth never
        lands in shared storage, so a truncated block with ``refcount !=
        1`` (or a published hash) means the allocator's COW discipline
        was violated upstream: that raises instead of freeing, the same
        engine-fatal posture as the step loop's write assertion.
        Returns the number of blocks reclaimed."""
        blocks = self._owned.get(seq_id)
        if blocks is None:
            raise CacheCapacityError(f"sequence {seq_id!r} owns no blocks")
        keep = max(0, int(keep))
        if keep >= len(blocks):
            return 0
        tail = blocks[keep:]
        for phys in tail:
            if self._ref.get(phys, 0) != 1 or phys in self._hash_of:
                raise InferenceServerException(
                    f"COW violation: speculative rollback of block "
                    f"{phys} (refcount {self._ref.get(phys, 0)}, "
                    f"published={phys in self._hash_of})"
                )
        for phys in reversed(tail):
            del self._ref[phys]
            self._free.append(phys)
        del blocks[keep:]
        return len(tail)

    def free(self, seq_id) -> int:
        """Drop a sequence's references (idempotent); returns the number
        of blocks actually RECLAIMED into the pool. A block another
        sequence still references survives with its index entry; the
        last reference unpublishes and reclaims it."""
        blocks = self._owned.pop(seq_id, None)
        if not blocks:
            return 0
        reclaimed = 0
        for phys in reversed(blocks):
            self._ref[phys] -= 1
            if self._ref[phys] > 0:
                continue
            del self._ref[phys]
            published = self._hash_of.pop(phys, None)
            if published is not None and self._index.get(published) == phys:
                del self._index[published]
            self._free.append(phys)
            reclaimed += 1
        return reclaimed

    # -- publication ---------------------------------------------------------

    def publish(self, seq_id, hashes: Sequence[bytes]) -> int:
        """Register a sequence's first ``len(hashes)`` blocks (its full,
        prefilled prompt blocks) in the shared index so later sequences
        can reference them. Blocks whose hash is already indexed (or that
        were themselves matched from the index) are skipped — first
        publisher wins, duplicates keep serving their own copy until
        freed. Returns the number of newly indexed blocks."""
        owned = self._owned.get(seq_id)
        if owned is None:
            return 0
        published = 0
        for phys, h in zip(owned, hashes):
            if phys in self._hash_of or h in self._index:
                continue
            self._index[h] = phys
            self._hash_of[phys] = h
            published += 1
        return published
